"""OpenrCtrlHandler: the unified control/introspection API.

Behavioral parity with the reference ``openr/ctrl-server/OpenrCtrlHandler``
(the ~70-RPC ``OpenrCtrl`` thrift service, openr/if/OpenrCtrl.thrift:168):
per-module getters/setters routed to the modules' thread-safe APIs, plus
server-streaming subscriptions for KvStore publications and Fib deltas
(reference: OpenrCtrlHandler.h:226-247) and KvStore adjacency long-poll
(:250).

This object is transport-neutral: used directly in-process, and exposed
over TCP by ``openr_tpu.ctrl.server.CtrlServer`` (the thrift-server
analogue) for the ``breeze`` CLI.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from openr_tpu.analysis.annotations import runs_on
from openr_tpu.messaging.queue import RQueue
from openr_tpu.types import (
    TTL_INFINITY,
    IpPrefix,
    KeyDumpParams,
    KeySetParams,
    Value,
)
from openr_tpu.types.lsdb import PrefixForwardingAlgorithm, PrefixForwardingType
from openr_tpu.types import PrefixEntry, PrefixType
from openr_tpu.utils import keys as keyutil


class _FilteredPublicationReader:
    """Reader adapter dropping publications outside the subscription's
    area / key-prefix and trimming the surviving ones to matching keys
    (the reference KvStorePublisher's per-subscriber filter,
    openr/kvstore/KvStorePublisher.h)."""

    def __init__(self, reader, prefix: str, area: str):
        self._reader = reader
        self._prefix = prefix
        self._area = area

    def get(self, timeout: Optional[float] = None):
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            remaining = (
                None if deadline is None else deadline - _time.monotonic()
            )
            pub = self._reader.get(timeout=remaining)
            if pub.area != self._area:
                continue
            if not self._prefix:
                return pub
            key_vals = {
                k: v
                for k, v in pub.key_vals.items()
                if k.startswith(self._prefix)
            }
            expired = [
                k for k in pub.expired_keys if k.startswith(self._prefix)
            ]
            if not key_vals and not expired:
                continue
            return type(pub)(
                key_vals=key_vals,
                expired_keys=expired,
                area=pub.area,
            )

    def close(self) -> None:
        close = getattr(self._reader, "close", None)
        if close is not None:
            close()


@runs_on("ctrl")
class OpenrCtrlHandler:
    def __init__(
        self,
        node_name: str,
        kvstore=None,
        decision=None,
        fib=None,
        link_monitor=None,
        prefix_manager=None,
        spark=None,
        monitor=None,
        config=None,
    ):
        self.node_name = node_name
        self._kvstore = kvstore
        self._decision = decision
        self._fib = fib
        self._link_monitor = link_monitor
        self._prefix_manager = prefix_manager
        self._spark = spark
        self._monitor = monitor
        self._config = config
        self._config_store = None  # wired by the daemon when present
        self._start_time = int(time.time())

    # -- fb303-style base -------------------------------------------------

    def alive_since(self) -> int:
        return self._start_time

    def get_my_node_name(self) -> str:
        """reference: OpenrCtrl.thrift getMyNodeName."""
        return self.node_name

    def dryrun_config(self, config_json: str) -> Dict[str, Any]:
        """Validate a config document server-side (reference:
        OpenrCtrl.thrift dryrunConfig)."""
        import json as _json

        from openr_tpu.config.config import ConfigError, OpenrConfig

        try:
            cfg = OpenrConfig.from_dict(_json.loads(config_json))
            return {"valid": True, "node_name": cfg.node_name}
        except (ConfigError, ValueError, KeyError, TypeError) as exc:
            return {"valid": False, "error": str(exc)}

    # -- config store (reference: getConfigKey / setConfigKey /
    # eraseConfigKey over PersistentStore) --------------------------------

    def get_config_key(self, key: str) -> Any:
        if self._config_store is None:
            return None
        return self._config_store.load(key)

    def set_config_key(self, key: str, value: Any) -> None:
        if self._config_store is None:
            raise RuntimeError("no persistent store configured")
        self._config_store.store(key, value)

    def erase_config_key(self, key: str) -> bool:
        if self._config_store is None:
            return False
        return self._config_store.erase(key)

    def get_counters(self) -> Dict[str, Any]:
        # start from the process-wide telemetry registry snapshot (the
        # store of record for SPF/ELL counters, latency histograms,
        # trace health, and jax compile metrics), then fold in the
        # module-local counter dicts — same order Monitor.get_counters
        # uses, so `breeze monitor counters` and this API agree
        from openr_tpu.telemetry import get_registry

        out: Dict[str, Any] = dict(get_registry().snapshot())
        for module in (
            self._kvstore,
            self._decision,
            self._fib,
            self._link_monitor,
            self._spark,
            self._monitor,
        ):
            if module is None:
                continue
            getter = getattr(module, "get_counters", None) or getattr(
                module, "counters", None
            )
            try:
                counters = getter() if callable(getter) else getter
                if counters:
                    out.update(counters)
            except Exception:
                continue
        return out

    def get_running_config(self) -> Dict[str, Any]:
        if self._config is None:
            return {"node_name": self.node_name}
        return self._config.to_dict()

    # -- KvStore ----------------------------------------------------------

    def get_kvstore_key_vals(
        self, keys: List[str], area: str = "0"
    ) -> Dict[str, Value]:
        return self._kvstore.get_key_vals(area, keys)

    def set_kvstore_key_vals(
        self, key_vals: Dict[str, Value], area: str = "0"
    ) -> None:
        self._kvstore.set_key_vals(
            area,
            KeySetParams(key_vals=key_vals, originator_id=self.node_name),
        )

    def set_kvstore_key(
        self,
        key: str,
        value: str,
        version: int = 0,
        area: str = "0",
        ttl: Optional[int] = None,
    ) -> int:
        """Operator-facing single-key set (breeze kvstore set-key):
        version 0 auto-advances past the stored version. Returns the
        version written."""
        if version == 0:
            cur = self._kvstore.get_key_vals(area, [key]).get(key)
            version = (cur.version + 1) if cur is not None else 1
        self._kvstore.set_key_vals(
            area,
            KeySetParams(
                key_vals={
                    key: Value(
                        version=version,
                        originator_id=self.node_name,
                        value=value.encode("utf-8"),
                        ttl=TTL_INFINITY if ttl is None else ttl,
                    )
                },
                originator_id=self.node_name,
            ),
        )
        return version

    def erase_kvstore_key(self, key: str, area: str = "0") -> bool:
        """Expire a key network-wide by re-advertising it with a bumped
        ttl_version and a near-zero TTL (the reference's breeze kvstore
        erase-key mechanism — TTL countdown then removes it everywhere)."""
        cur = self._kvstore.get_key_vals(area, [key]).get(key)
        if cur is None:
            return False
        self._kvstore.set_key_vals(
            area,
            KeySetParams(
                key_vals={
                    key: Value(
                        version=cur.version,
                        originator_id=cur.originator_id,
                        value=cur.value,
                        ttl=100,  # ms: floods, then dies everywhere
                        ttl_version=cur.ttl_version + 1,
                    )
                },
                originator_id=self.node_name,
            ),
        )
        return True

    def get_kvstore_keys_filtered(
        self, prefix: str = "", area: str = "0"
    ) -> Dict[str, Value]:
        return self._kvstore.dump_with_filters(
            area, KeyDumpParams(prefix=prefix)
        ).key_vals

    def get_kvstore_hash_filtered(
        self, prefix: str = "", area: str = "0"
    ) -> Dict[str, Value]:
        return self._kvstore.dump_hashes(area, prefix).key_vals

    def get_kvstore_peers(self, area: str = "0") -> Dict[str, str]:
        return {
            name: state.name
            for name, state in self._kvstore.peer_states(area).items()
        }

    def get_kvstore_areas(self) -> List[str]:
        return self._kvstore.areas()

    def get_spanning_tree_infos(self, area: str = "0"):
        """reference: OpenrCtrl.thrift getSpanningTreeInfos — the
        flood-optimization SPT snapshot (per-root state + elected
        flood root + flooding peers); empty when DUAL is off."""
        return self._kvstore.spt_infos(area)

    def subscribe_kvstore_filtered(
        self, prefix: str = "", area: str = "0"
    ):
        """Server-streaming subscription (reference:
        OpenrCtrlHandler.h:226 subscribeAndGetKvStoreFiltered +
        KvStorePublisher's filtered fan-out). Returns a reader delivering
        only Publications touching the requested area/key-prefix;
        snapshot via get_kvstore_keys_filtered first."""
        reader = self._kvstore.updates_queue.get_reader(
            f"ctrl-sub:{self.node_name}"
        )
        if not prefix and area == "0" and self._kvstore.areas() == ["0"]:
            return reader
        return _FilteredPublicationReader(reader, prefix, area)

    def long_poll_kvstore_adj(
        self, area: str = "0", timeout_s: float = 10.0
    ) -> bool:
        """Block until any adj: key changes (reference:
        OpenrCtrlHandler.h:250 longPollKvStoreAdj). Returns True if a
        change was seen within the timeout."""
        reader = self._kvstore.updates_queue.get_reader("ctrl-longpoll")
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            try:
                pub = reader.get(timeout=remaining)
            except Exception:
                return False
            if pub.area != area:
                continue
            if any(keyutil.is_adj_key(k) for k in pub.key_vals) or any(
                keyutil.is_adj_key(k) for k in pub.expired_keys
            ):
                return True

    # -- Decision ---------------------------------------------------------

    def get_route_db_computed(self, node: Optional[str] = None):
        return self._decision.get_decision_route_db(node).to_route_db(
            node or self.node_name
        )

    def get_decision_adjacency_dbs(self):
        return self._decision.get_adj_dbs()

    def set_rib_policy(
        self, statements: List[Dict], ttl_secs: float = 300.0
    ) -> None:
        """reference: OpenrCtrl.thrift setRibPolicy."""
        from openr_tpu.decision.rib_policy import (
            RibPolicy,
            RibPolicyStatement,
            RibRouteAction,
            RibRouteActionWeight,
        )

        parsed = [
            RibPolicyStatement(
                name=s.get("name", ""),
                prefixes=tuple(
                    IpPrefix.from_str(p) for p in s.get("prefixes", [])
                ),
                action=RibRouteAction(
                    set_weight=RibRouteActionWeight(
                        default_weight=s.get("default_weight", 0),
                        area_to_weight=s.get("area_to_weight", {}),
                        neighbor_to_weight=s.get("neighbor_to_weight", {}),
                    )
                ),
            )
            for s in statements
        ]
        self._decision.set_rib_policy(RibPolicy(parsed, ttl_secs=ttl_secs))

    def get_rib_policy(self):
        policy = self._decision.get_rib_policy()
        if policy is None:
            return None
        def action_dict(action):
            w = action.set_weight
            if w is None:
                return {}
            return {
                "set_weight": {
                    "default_weight": w.default_weight,
                    "area_to_weight": dict(w.area_to_weight),
                    "neighbor_to_weight": dict(w.neighbor_to_weight),
                }
            }

        return {
            "ttl_remaining_s": policy.get_ttl_remaining_s(),
            "statements": [
                {
                    "name": s.name,
                    "prefixes": [p.to_str() for p in s.prefixes],
                    "action": action_dict(s.action),
                }
                for s in policy.statements
            ],
        }

    def get_decision_prefix_dbs(self):
        return self._decision.evb.call_and_wait(
            lambda: dict(self._decision.prefix_state.prefixes())
        )

    # -- Fib --------------------------------------------------------------

    def get_route_db(self):
        return self._fib.get_route_db()

    def get_unicast_routes(self, prefixes: Optional[List[str]] = None):
        parsed = (
            [IpPrefix.from_str(p) for p in prefixes] if prefixes else None
        )
        return self._fib.get_unicast_routes(parsed)

    def longest_prefix_match(self, addr: str):
        return self._fib.longest_prefix_match(addr)

    def subscribe_fib(self) -> RQueue:
        """reference: OpenrCtrlHandler.h:240 subscribeAndGetFib."""
        return self._fib.fib_updates_queue.get_reader(
            f"ctrl-fib-sub:{self.node_name}"
        )

    def get_perf_db(self):
        """reference: if/OpenrCtrl.thrift:312 getPerfDb."""
        return self._fib.evb.call_and_wait(lambda: list(self._fib.perf_db))

    def get_traces(
        self, limit: int = 20, fmt: str = "dict"
    ) -> Any:
        """Completed publication->FIB telemetry traces from the
        process-wide ring (newest last). fmt: "dict" (list of trace
        dicts), "jsonl", or "chrome" (one traceEvents document)."""
        from openr_tpu.telemetry import get_tracer

        tracer = get_tracer()
        if fmt == "chrome":
            return tracer.chrome_trace(limit)
        if fmt == "jsonl":
            return tracer.jsonl(limit)
        return [t.to_dict() for t in tracer.traces(limit)]

    def get_flight_record(self, limit: int = 0) -> Dict[str, Any]:
        """The flight recorder's recent-activity ring (newest last)
        plus the live device-time attribution — the first stop of the
        post-mortem triage recipe (docs/RUNBOOK.md)."""
        from openr_tpu.telemetry import get_flight_recorder, get_profiler

        fr = get_flight_recorder()
        prof = get_profiler()
        return {
            "records": fr.records(limit),
            "triggers": fr.trigger_names(),
            "attribution": prof.attribution(),
            "host_overhead_ratio": prof.host_overhead_ratio(),
        }

    def dump_postmortem(self, trigger: str = "manual",
                        reason: str = "") -> Dict[str, Any]:
        """Force a post-mortem bundle to disk right now (counted
        ``flight.dumps.manual`` unless a trigger name is given)."""
        from openr_tpu.telemetry import get_flight_recorder

        path = get_flight_recorder().dump_postmortem(
            trigger=trigger, reason=reason or "operator request"
        )
        return {"path": path}

    # -- LinkMonitor ------------------------------------------------------

    def get_interfaces(self):
        return self._link_monitor.get_interfaces()

    def get_link_monitor_adjacencies(self):
        return self._link_monitor.get_adjacencies()

    def set_node_overload(self, overloaded: bool) -> None:
        self._link_monitor.set_node_overload(overloaded)

    def set_link_overload(self, if_name: str, overloaded: bool) -> None:
        self._link_monitor.set_link_overload(if_name, overloaded)

    def set_link_metric(
        self, if_name: str, neighbor: str, metric: Optional[int]
    ) -> None:
        self._link_monitor.set_link_metric(if_name, neighbor, metric)

    # -- PrefixManager ----------------------------------------------------

    def set_interface_metric(self, if_name: str, metric: int) -> None:
        """reference: OpenrCtrl.thrift setInterfaceMetric."""
        self._link_monitor.set_interface_metric(if_name, metric)

    def unset_interface_metric(self, if_name: str) -> None:
        self._link_monitor.set_interface_metric(if_name, None)

    def get_prefixes(self):
        return self._prefix_manager.get_prefixes()

    def advertise_prefixes(
        self,
        prefixes: List[str],
        prefix_type: str = "BREEZE",
        forwarding_type: str = "IP",
        forwarding_algorithm: str = "SP_ECMP",
    ) -> None:
        entries = [
            PrefixEntry(
                prefix=IpPrefix.from_str(p),
                type=PrefixType[prefix_type],
                forwarding_type=PrefixForwardingType[forwarding_type],
                forwarding_algorithm=PrefixForwardingAlgorithm[
                    forwarding_algorithm
                ],
            )
            for p in prefixes
        ]
        self._prefix_manager.advertise_prefixes(entries)

    def withdraw_prefixes(self, prefixes: List[str]) -> None:
        self._prefix_manager.withdraw_prefixes(
            [IpPrefix.from_str(p) for p in prefixes]
        )

    def get_prefixes_by_type(self, prefix_type: str):
        """reference: OpenrCtrl.thrift getPrefixesByType."""
        want = PrefixType[prefix_type]
        return [
            e for e in self._prefix_manager.get_prefixes() if e.type == want
        ]

    def withdraw_prefixes_by_type(self, prefix_type: str) -> int:
        """reference: OpenrCtrl.thrift withdrawPrefixesByType."""
        victims = [e.prefix for e in self.get_prefixes_by_type(prefix_type)]
        if victims:
            self._prefix_manager.withdraw_prefixes(victims)
        return len(victims)

    def sync_prefixes_by_type(
        self,
        prefix_type: str,
        prefixes: List[str],
    ) -> None:
        """reference: OpenrCtrl.thrift syncPrefixesByType — the given set
        becomes the complete set for that type."""
        ptype = PrefixType[prefix_type]
        entries = [
            PrefixEntry(prefix=IpPrefix.from_str(p), type=ptype)
            for p in prefixes
        ]
        self._prefix_manager.sync_prefixes_by_type(ptype, entries)

    def get_advertised_routes(self, prefix: str = ""):
        """reference: OpenrCtrl.thrift getAdvertisedRoutes(Filtered)."""
        out = self._prefix_manager.get_prefixes()
        if prefix:
            want = IpPrefix.from_str(prefix)
            out = [e for e in out if e.prefix == want]
        return out

    def get_received_routes(self, prefix: str = ""):
        """reference: OpenrCtrl.thrift getReceivedRoutes(Filtered) — the
        per-prefix advertisements Decision has received, with their
        advertising (node, area)s."""
        dbs = self._decision.evb.call_and_wait(
            lambda: dict(self._decision.prefix_state.prefixes())
        )
        if prefix:
            want = IpPrefix.from_str(prefix)
            dbs = {p: entries for p, entries in dbs.items() if p == want}
        return dbs

    # -- Spark ------------------------------------------------------------

    def flood_restarting_msg(self) -> None:
        """reference: OpenrCtrl.thrift floodRestartingMsg — announce
        graceful restart on every interface without stopping."""
        self._spark.flood_restarting()

    def get_spark_neighbors(self):
        return {
            if_name: {n: state.name for n, state in neighbors.items()}
            for if_name, neighbors in self._spark.get_neighbors().items()
        }

    # -- Monitor ----------------------------------------------------------

    def get_event_logs(self, limit: int = 100):
        if self._monitor is None:
            return []
        return [s.to_json() for s in self._monitor.get_event_logs(limit)]
