"""breeze: the operator CLI.

Command-group parity with the reference ``openr/py/openr/cli/breeze.py``
(groups: config, decision, fib, kvstore, lm, monitor, openr, perf,
prefixmgr, spark, tech-support; breeze.py:94-104). Talks to a running
daemon's CtrlServer over TCP, or drives an in-process handler directly
(used by tests and the simulator).

Usage:  breeze [--host H] [--port P] <group> <command> [args...]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Callable, Dict, List, Optional

from openr_tpu.cli.printing import caption, render_table


class _InProcessClient:
    """Adapter giving an OpenrCtrlHandler the CtrlClient interface."""

    def __init__(self, handler):
        self._handler = handler

    def call(self, method: str, **kwargs) -> Any:
        from openr_tpu.utils.jsonable import to_jsonable

        return to_jsonable(getattr(self._handler, method)(**kwargs))

    def close(self) -> None:
        pass


def _fmt_next_hop(nh: Dict) -> str:
    out = str(nh.get("address", ""))
    mpls = nh.get("mpls_action")
    if mpls:
        action = mpls.get("action")
        if action == "PUSH":
            out += f" mpls push {mpls.get('push_labels')}"
        elif action == "SWAP":
            out += f" mpls swap {mpls.get('swap_label')}"
        else:
            out += f" mpls {str(action).lower()}"
    out += f" metric {nh.get('metric')}"
    if nh.get("neighbor_node_name"):
        out += f" via {nh['neighbor_node_name']}"
    return out


class Breeze:
    def __init__(self, client, out=None):
        self.client = client
        self.out = out or sys.stdout

    def _print(self, text: str) -> None:
        print(text, file=self.out)

    # -- decision ---------------------------------------------------------

    def decision_routes(self, node: Optional[str] = None) -> None:
        db = self.client.call("get_route_db_computed", node=node)
        self._print(caption(f"Routes computed for {db.get('this_node_name')}"))
        rows = []
        for route in db.get("unicast_routes", []):
            nhs = [_fmt_next_hop(nh) for nh in route.get("next_hops", [])]
            rows.append((route.get("dest"), "\n".join(nhs) or "-"))
        self._print(render_table(["Prefix", "NextHops"], rows))

    def decision_adj(self) -> None:
        dbs = self.client.call("get_decision_adjacency_dbs")
        for area, nodes in sorted(dbs.items()):
            self._print(caption(f"Area {area}"))
            rows = []
            for node, adj_db in sorted(nodes.items()):
                for adj in adj_db.get("adjacencies", []):
                    rows.append(
                        (
                            node,
                            adj.get("other_node_name"),
                            adj.get("if_name"),
                            adj.get("metric"),
                            adj.get("rtt"),
                            "overloaded" if adj.get("is_overloaded") else "",
                        )
                    )
            self._print(
                render_table(
                    ["Node", "Neighbor", "Iface", "Metric", "RTT(us)", ""],
                    rows,
                )
            )

    def decision_rib_policy(self) -> None:
        """reference: breeze decision rib-policy (show the installed
        TTL'd policy)."""
        policy = self.client.call("get_rib_policy")
        if policy is None:
            self._print("no rib policy installed")
            return
        self._print(
            caption(
                f"RibPolicy (ttl remaining: "
                f"{policy.get('ttl_remaining_s', 0):.1f}s)"
            )
        )

        def fmt_action(action):
            w = (action or {}).get("set_weight")
            if not w:
                return "-"
            parts = [f"default={w.get('default_weight', 0)}"]
            parts += [
                f"area {a}={v}"
                for a, v in sorted(w.get("area_to_weight", {}).items())
            ]
            parts += [
                f"nbr {n}={v}"
                for n, v in sorted(
                    w.get("neighbor_to_weight", {}).items()
                )
            ]
            return ", ".join(parts)

        rows = [
            (
                s.get("name", ""),
                ", ".join(s.get("prefixes", [])),
                fmt_action(s.get("action")),
            )
            for s in policy.get("statements", [])
        ]
        self._print(
            render_table(["Statement", "Prefixes", "SetWeight"], rows)
        )

    def decision_prefixes(self) -> None:
        dbs = self.client.call("get_decision_prefix_dbs")
        rows = []
        for prefix, entries in sorted(dbs.items()):
            for node_area, entry in sorted(entries.items()):
                rows.append(
                    (
                        prefix,
                        node_area,
                        entry.get("type"),
                        entry.get("forwarding_algorithm"),
                    )
                )
        self._print(render_table(["Prefix", "Node|Area", "Type", "Algo"], rows))

    # -- fib --------------------------------------------------------------

    def fib_routes(self) -> None:
        db = self.client.call("get_route_db")
        self._print(caption(f"FIB routes on {db.get('this_node_name')}"))
        rows = []
        for route in db.get("unicast_routes", []):
            nhs = [_fmt_next_hop(nh) for nh in route.get("next_hops", [])]
            rows.append((route.get("dest"), "\n".join(nhs) or "-"))
        self._print(render_table(["Prefix", "NextHops"], rows))
        mpls_rows = [
            (
                r.get("top_label"),
                "\n".join(
                    _fmt_next_hop(nh) for nh in r.get("next_hops", [])
                ),
            )
            for r in db.get("mpls_routes", [])
        ]
        if mpls_rows:
            self._print(render_table(["Label", "NextHops"], mpls_rows))

    def fib_counters(self) -> None:
        counters = self.client.call("get_counters")
        rows = [(k, v) for k, v in sorted(counters.items()) if "fib" in k]
        self._print(render_table(["Counter", "Value"], rows))

    # -- kvstore ----------------------------------------------------------

    def kvstore_keys(self, prefix: str = "", area: str = "0") -> None:
        key_vals = self.client.call(
            "get_kvstore_keys_filtered", prefix=prefix, area=area
        )
        rows = []
        for key, value in sorted(key_vals.items()):
            rows.append(
                (
                    key,
                    value.get("originator_id"),
                    value.get("version"),
                    value.get("ttl"),
                    value.get("ttl_version"),
                )
            )
        self._print(
            render_table(
                ["Key", "Originator", "Version", "TTL(ms)", "TTLv"], rows
            )
        )

    def kvstore_get_key(self, key: str, area: str = "0") -> None:
        vals = self.client.call("get_kvstore_key_vals", keys=[key], area=area)
        val = vals.get(key)
        if val is None:
            self._print(f"{key}: not found")
            raise SystemExit(1)
        raw = val.get("value")
        if isinstance(raw, dict) and "__bytes__" in raw:
            data = bytes.fromhex(raw["__bytes__"])
            try:
                val["value"] = data.decode("utf-8")
            except UnicodeDecodeError:
                val["value"] = raw["__bytes__"]  # keep hex for binary
        self._print(json.dumps(val, indent=2))

    def kvstore_set_key(
        self, key: str, value: str, version: int = 0, area: str = "0"
    ) -> None:
        written = self.client.call(
            "set_kvstore_key", key=key, value=value, version=version,
            area=area,
        )
        self._print(f"set {key} at version {written}")

    def kvstore_erase_key(self, key: str, area: str = "0") -> None:
        ok = self.client.call("erase_kvstore_key", key=key, area=area)
        if not ok:
            self._print(f"{key}: not found")
            raise SystemExit(1)
        self._print(f"erasing {key} (ttl countdown)")

    def kvstore_peers(self, area: str = "0") -> None:
        peers = self.client.call("get_kvstore_peers", area=area)
        self._print(
            render_table(["Peer", "State"], sorted(peers.items()))
        )

    def kvstore_areas(self) -> None:
        areas = self.client.call("get_kvstore_areas")
        self._print(render_table(["Area"], [(a,) for a in areas]))

    def kvstore_flood(self, area: str = "0") -> None:
        """reference: breeze kvstore flood — the DUAL spanning-tree
        snapshot (per-root state, elected flood root, flooding peers)."""
        snap = self.client.call("get_spanning_tree_infos", area=area)
        root = snap.get("flood_root_id")
        self._print(f"flood root: {root if root is not None else '-'}")
        peers = sorted(snap.get("flood_peers", ()))
        self._print(f"flood peers: {', '.join(peers) if peers else '-'}")
        rows = [
            (
                rid,
                "PASSIVE" if info.get("passive") else "ACTIVE",
                info.get("cost"),
                info.get("parent") or "-",
                ", ".join(sorted(info.get("children", ()))) or "-",
            )
            for rid, info in sorted(snap.get("infos", {}).items())
        ]
        self._print(render_table(
            ["Root", "State", "Cost", "Parent", "Children"], rows
        ))

    # -- lm ---------------------------------------------------------------

    def lm_links(self) -> None:
        interfaces = self.client.call("get_interfaces")
        rows = [
            (
                name,
                "UP" if info.get("is_up") else "DOWN",
                ", ".join(info.get("networks", [])),
            )
            for name, info in sorted(interfaces.items())
        ]
        self._print(render_table(["Interface", "State", "Addresses"], rows))

    def lm_adj(self) -> None:
        adj_db = self.client.call("get_link_monitor_adjacencies")
        rows = [
            (
                adj.get("other_node_name"),
                adj.get("if_name"),
                adj.get("metric"),
                adj.get("rtt"),
            )
            for adj in adj_db.get("adjacencies", [])
        ]
        overload = "OVERLOADED" if adj_db.get("is_overloaded") else "healthy"
        self._print(caption(f"Node {adj_db.get('this_node_name')} ({overload})"))
        self._print(
            render_table(["Neighbor", "Iface", "Metric", "RTT(us)"], rows)
        )

    def lm_set_node_overload(self) -> None:
        self.client.call("set_node_overload", overloaded=True)
        self._print("node overload: SET")

    def lm_unset_node_overload(self) -> None:
        self.client.call("set_node_overload", overloaded=False)
        self._print("node overload: UNSET")

    def lm_set_link_overload(self, if_name: str) -> None:
        self.client.call(
            "set_link_overload", if_name=if_name, overloaded=True
        )
        self._print(f"link overload on {if_name}: SET")

    def lm_unset_link_overload(self, if_name: str) -> None:
        self.client.call(
            "set_link_overload", if_name=if_name, overloaded=False
        )
        self._print(f"link overload on {if_name}: UNSET")

    def lm_set_link_metric(self, if_name: str, neighbor: str, metric: int):
        self.client.call(
            "set_link_metric",
            if_name=if_name,
            neighbor=neighbor,
            metric=metric,
        )
        self._print(f"metric override {if_name}->{neighbor} = {metric}")

    def lm_set_interface_metric(self, if_name: str, metric: int):
        """reference: breeze lm set-link-metric (interface-wide)."""
        self.client.call(
            "set_interface_metric", if_name=if_name, metric=metric
        )
        self._print(f"interface metric override {if_name} = {metric}")

    def lm_unset_interface_metric(self, if_name: str):
        self.client.call("unset_interface_metric", if_name=if_name)
        self._print(f"interface metric override {if_name} cleared")

    def lm_unset_link_metric(self, if_name: str, neighbor: str) -> None:
        self.client.call(
            "set_link_metric", if_name=if_name, neighbor=neighbor, metric=None
        )
        self._print(f"metric override {if_name}->{neighbor} cleared")

    # -- monitor ----------------------------------------------------------

    def monitor_counters(self) -> None:
        counters = self.client.call("get_counters")
        self._print(
            render_table(["Counter", "Value"], sorted(counters.items()))
        )

    def monitor_logs(self, limit: int = 20) -> None:
        logs = self.client.call("get_event_logs", limit=limit)
        for raw in logs:
            self._print(raw if isinstance(raw, str) else json.dumps(raw))

    def monitor_traces(
        self, limit: int = 20, fmt: str = "table"
    ) -> None:
        """Completed publication->FIB convergence traces. "table" for a
        per-trace span summary; "jsonl"/"chrome" dump the raw artifact
        (chrome loads in chrome://tracing or ui.perfetto.dev)."""
        if fmt in ("jsonl", "chrome"):
            out = self.client.call("get_traces", limit=limit, fmt=fmt)
            self._print(
                out if isinstance(out, str) else json.dumps(out)
            )
            return
        traces = self.client.call("get_traces", limit=limit)
        rows = []
        for t in traces:
            spans = " > ".join(
                "  " * s["depth"] + f"{s['name']}={s['dur_ms']}ms"
                for s in t["spans"]
            )
            rows.append(
                (
                    t["trace_id"],
                    "ok" if t["complete"] else "INCOMPLETE",
                    t["e2e_ms"],
                    spans,
                )
            )
        self._print(
            render_table(["Trace", "State", "e2e_ms", "Spans"], rows)
        )

    def monitor_flight(self, limit: int = 30, dump: bool = False,
                       fmt: str = "table") -> None:
        """The flight recorder's recent-activity ring + live per-stage
        device-time attribution; ``--dump`` forces a post-mortem
        bundle to disk on the server and prints its path."""
        if dump:
            out = self.client.call(
                "dump_postmortem", trigger="manual",
                reason="breeze monitor flight --dump",
            )
            path = out.get("path")
            if path:
                self._print(f"post-mortem bundle: {path}")
            else:
                self._print(
                    "post-mortem dump produced no bundle (rate-limited,"
                    " disabled, or write failed server-side)"
                )
            return
        rec = self.client.call("get_flight_record", limit=limit)
        if fmt == "json":
            self._print(json.dumps(rec, indent=2))
            return
        rows = []
        for r in rec["records"]:
            extra = {
                k: v for k, v in r.items() if k not in ("ts", "kind")
            }
            rows.append((r["ts"], r["kind"], json.dumps(extra)))
        self._print(render_table(["ts", "kind", "detail"], rows))
        attr_rows = [
            (
                tag,
                row.get("device_ms_p50"),
                row.get("host_ms_p50"),
                row.get("calls"),
                row.get("device_samples"),
            )
            for tag, row in sorted(rec["attribution"].items())
        ]
        self._print(
            render_table(
                ["Stage", "device_ms_p50", "host_ms_p50", "calls",
                 "samples"],
                attr_rows,
            )
        )
        self._print(
            f"host_overhead_ratio={rec['host_overhead_ratio']} "
            f"triggers={','.join(rec['triggers']) or '(none)'}"
        )

    def monitor_replay(self, bundle: str, as_json: bool = False,
                       backend: str = "device",
                       twice: bool = False) -> None:
        """LOCAL command (no daemon dial): deterministically re-run a
        post-mortem bundle's captured churn through a fresh FabricTwin
        and print the verdict — the bundle is self-contained, so this
        works on any box with the repo, not just the one that dumped
        it. ``--twice`` replays twice and checks the per-vantage route
        digests are bit-identical across runs."""
        from openr_tpu.twin.replay import ScenarioReplayer, replay_digest

        verdict = ScenarioReplayer.from_path(
            bundle, solver_backend=backend
        ).replay()
        deterministic = None
        if twice:
            second = ScenarioReplayer.from_path(
                bundle, solver_backend=backend
            ).replay()
            deterministic = (
                replay_digest(verdict) == replay_digest(second)
            )
        if as_json:
            out = verdict.to_dict()
            out["deterministic"] = deterministic
            self._print(json.dumps(out, indent=2, sort_keys=True))
            return
        self._print(
            f"reproduced={verdict.reproduced} "
            f"recorded={sorted(verdict.recorded_classes)} "
            f"replayed={sorted(verdict.replayed_classes)}"
        )
        self._print(
            f"windows={verdict.windows} pubs={verdict.pubs_applied} "
            f"trailing_pubs={verdict.trailing_pubs} "
            f"anchor_moved={verdict.anchor_moved} "
            f"digests_match_recorded={verdict.digests_match_recorded}"
        )
        if deterministic is not None:
            self._print(f"deterministic={deterministic}")
        for d in verdict.divergence[:10]:
            self._print(f"  divergence: {json.dumps(d, sort_keys=True)}")
        for e in verdict.errors:
            self._print(f"  error: {e}")

    # -- openr ------------------------------------------------------------

    def openr_version(self) -> None:
        import openr_tpu

        self._print(f"openr-tpu {openr_tpu.__version__}")

    def openr_config(self) -> None:
        self._print(json.dumps(self.client.call("get_running_config"), indent=2))

    # -- config -----------------------------------------------------------
    # reference: py/openr/cli/clis/config.py (show / dryrun / compare)

    def config_show(self) -> None:
        self._print(
            json.dumps(self.client.call("get_running_config"), indent=2)
        )

    def config_dryrun(self, path: str) -> None:
        """Parse + validate a config file locally; no daemon needed."""
        from openr_tpu.config.config import OpenrConfig

        try:
            cfg = OpenrConfig.from_file(path)
        except Exception as exc:  # noqa: BLE001 - report, exit non-zero
            self._print(f"INVALID: {exc}")
            raise SystemExit(1)
        self._print(f"OK: valid config for node {cfg.node_name!r}")

    def config_compare(self, path: str) -> None:
        """Diff a config file against the daemon's running config."""
        from openr_tpu.config.config import OpenrConfig

        running = self.client.call("get_running_config")
        local = OpenrConfig.from_file(path).to_dict()
        keys = sorted(set(running) | set(local))
        rows = [
            (k, json.dumps(running.get(k)), json.dumps(local.get(k)))
            for k in keys
            if running.get(k) != local.get(k)
        ]
        if not rows:
            self._print("identical")
        else:
            self._print(render_table(["Field", "Running", "File"], rows))

    def config_store_get(self, key: str) -> None:
        """reference: OpenrCtrl getConfigKey over the PersistentStore."""
        value = self.client.call("get_config_key", key=key)
        if value is None:
            self._print(f"{key}: not found")
            raise SystemExit(1)
        self._print(json.dumps(value))

    def config_store_set(self, key: str, value: str) -> None:
        try:
            self.client.call("set_config_key", key=key, value=value)
        except Exception as exc:  # e.g. no persistent store configured
            self._print(f"error: {exc}")
            raise SystemExit(1)
        self._print(f"stored {key}")

    def config_store_erase(self, key: str) -> None:
        ok = self.client.call("erase_config_key", key=key)
        if not ok:
            self._print(f"{key}: not found")
            raise SystemExit(1)
        self._print(f"erased {key}")

    # -- perf -------------------------------------------------------------

    def perf_fib(self) -> None:
        perf_db = self.client.call("get_perf_db")
        for events in perf_db:
            rows = []
            prev_ts = None
            for ev in events.get("events", []):
                ts = ev.get("unix_ts")
                delta = "" if prev_ts is None else f"+{ts - prev_ts}ms"
                prev_ts = ts
                rows.append((ev.get("node_name"), ev.get("event_descr"), ts, delta))
            self._print(
                render_table(["Node", "Event", "Unix-ts(ms)", "Delta"], rows)
            )
            self._print("")

    # -- prefixmgr --------------------------------------------------------

    def prefixmgr_view(self) -> None:
        prefixes = self.client.call("get_prefixes")
        rows = [
            (
                p.get("prefix"),
                p.get("type"),
                p.get("forwarding_type"),
                p.get("forwarding_algorithm"),
            )
            for p in prefixes
        ]
        self._print(render_table(["Prefix", "Type", "Fwd", "Algo"], rows))

    def prefixmgr_advertise(self, prefixes: List[str]) -> None:
        self.client.call("advertise_prefixes", prefixes=prefixes)
        self._print(f"advertised {len(prefixes)} prefixes")

    def prefixmgr_withdraw(self, prefixes: List[str]) -> None:
        self.client.call("withdraw_prefixes", prefixes=prefixes)
        self._print(f"withdrew {len(prefixes)} prefixes")

    def prefixmgr_sync(
        self, prefix_type: str, prefixes: List[str]
    ) -> None:
        """reference: breeze prefixmgr sync — the given set becomes
        the COMPLETE set for the type (empty withdraws everything)."""
        self.client.call(
            "sync_prefixes_by_type",
            prefix_type=prefix_type, prefixes=prefixes,
        )
        self._print(
            f"synced {len(prefixes)} prefixes for type {prefix_type}"
        )

    def prefixmgr_advertised_routes(self) -> None:
        """reference: breeze prefixmgr advertised-routes."""
        entries = self.client.call("get_advertised_routes")
        rows = [
            (
                e.get("prefix"),
                e.get("type"),
                (e.get("metrics") or {}).get("path_preference"),
                (e.get("metrics") or {}).get("source_preference"),
            )
            for e in entries
        ]
        self._print(render_table(
            ["Prefix", "Type", "PathPref", "SrcPref"], rows
        ))

    # -- spark ------------------------------------------------------------

    def spark_neighbors(self) -> None:
        neighbors = self.client.call("get_spark_neighbors")
        rows = []
        for if_name, by_node in sorted(neighbors.items()):
            for node, state in sorted(by_node.items()):
                rows.append((if_name, node, state))
        self._print(render_table(["Iface", "Neighbor", "State"], rows))

    # -- tech-support -----------------------------------------------------

    def tech_support(self) -> None:
        self.openr_version()
        self.monitor_counters()
        self.kvstore_areas()
        self.kvstore_keys()
        self.decision_adj()
        self.fib_routes()
        self.lm_links()


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="breeze")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=2018)
    sub = parser.add_subparsers(dest="group", required=True)

    def group(name):
        g = sub.add_parser(name)
        return g.add_subparsers(dest="command", required=True)

    c = group("config")
    c.add_parser("show")
    p = c.add_parser("dryrun")
    p.add_argument("file")
    p = c.add_parser("compare")
    p.add_argument("file")
    p = c.add_parser("store-get")
    p.add_argument("key")
    p = c.add_parser("store-set")
    p.add_argument("key")
    p.add_argument("value")
    p = c.add_parser("store-erase")
    p.add_argument("key")

    d = group("decision")
    routes = d.add_parser("routes")
    routes.add_argument("--node", default=None)
    d.add_parser("adj")
    d.add_parser("prefixes")
    d.add_parser("rib-policy")

    f = group("fib")
    f.add_parser("routes")
    f.add_parser("counters")

    k = group("kvstore")
    p = k.add_parser("get-key")
    p.add_argument("key")
    p.add_argument("--area", default="0")
    p = k.add_parser("set-key")
    p.add_argument("key")
    p.add_argument("value")
    p.add_argument("--version", type=int, default=0)
    p.add_argument("--area", default="0")
    p = k.add_parser("erase-key")
    p.add_argument("key")
    p.add_argument("--area", default="0")
    keys = k.add_parser("keys")
    keys.add_argument("--prefix", default="")
    keys.add_argument("--area", default="0")
    peers = k.add_parser("peers")
    peers.add_argument("--area", default="0")
    k.add_parser("areas")
    flood = k.add_parser("flood")
    flood.add_argument("--area", default="0")

    lm = group("lm")
    lm.add_parser("links")
    lm.add_parser("adj")
    lm.add_parser("set-node-overload")
    lm.add_parser("unset-node-overload")
    p = lm.add_parser("set-link-overload")
    p.add_argument("interface")
    p = lm.add_parser("unset-link-overload")
    p.add_argument("interface")
    p = lm.add_parser("set-link-metric")
    p.add_argument("interface")
    p.add_argument("neighbor")
    p.add_argument("metric", type=int)
    p = lm.add_parser("unset-link-metric")
    p.add_argument("interface")
    p.add_argument("neighbor")
    # reference naming: set-adj-metric is the per-adjacency override
    # (what set-link-metric above already does here); set-interface-
    # metric is the interface-wide override
    p = lm.add_parser("set-adj-metric")
    p.add_argument("interface")
    p.add_argument("neighbor")
    p.add_argument("metric", type=int)
    p = lm.add_parser("unset-adj-metric")
    p.add_argument("interface")
    p.add_argument("neighbor")
    p = lm.add_parser("set-interface-metric")
    p.add_argument("interface")
    p.add_argument("metric", type=int)
    p = lm.add_parser("unset-interface-metric")
    p.add_argument("interface")

    m = group("monitor")
    m.add_parser("counters")
    logs = m.add_parser("logs")
    logs.add_argument("--limit", type=int, default=20)
    traces = m.add_parser("traces")
    traces.add_argument("--limit", type=int, default=20)
    traces.add_argument(
        "--format",
        dest="fmt",
        choices=("table", "jsonl", "chrome"),
        default="table",
    )
    flight = m.add_parser("flight")
    flight.add_argument("--limit", type=int, default=30)
    flight.add_argument("--dump", action="store_true")
    flight.add_argument(
        "--format",
        dest="fmt",
        choices=("table", "json"),
        default="table",
    )
    replay = m.add_parser("replay")
    replay.add_argument("bundle")
    replay.add_argument("--json", dest="as_json", action="store_true")
    replay.add_argument("--backend", default="device")
    replay.add_argument("--twice", action="store_true")

    o = group("openr")
    o.add_parser("version")
    o.add_parser("config")

    perf = group("perf")
    perf.add_parser("fib")

    pm = group("prefixmgr")
    pm.add_parser("view")
    adv = pm.add_parser("advertise")
    adv.add_argument("prefixes", nargs="+")
    wd = pm.add_parser("withdraw")
    wd.add_argument("prefixes", nargs="+")
    sync = pm.add_parser("sync")
    sync.add_argument("--type", dest="prefix_type", default="BREEZE")
    sync.add_argument("prefixes", nargs="*")
    pm.add_parser("advertised-routes")

    s = group("spark")
    s.add_parser("neighbors")

    sub.add_parser("tech-support")
    return parser


def run(argv: List[str], client=None, out=None) -> int:
    args = build_parser().parse_args(argv)
    group = args.group.replace("-", "_")
    command = getattr(args, "command", "").replace("-", "_") if hasattr(
        args, "command"
    ) else ""
    local = group == "monitor" and command == "replay"
    if client is None and not local:
        from openr_tpu.ctrl.server import CtrlClient

        client = CtrlClient(args.host, args.port)
    breeze = Breeze(client, out=out)

    dispatch: Dict[str, Callable[[], None]] = {
        "config.show": breeze.config_show,
        "config.dryrun": lambda: breeze.config_dryrun(args.file),
        "config.compare": lambda: breeze.config_compare(args.file),
        "config.store_get": lambda: breeze.config_store_get(args.key),
        "config.store_set": lambda: breeze.config_store_set(
            args.key, args.value
        ),
        "config.store_erase": lambda: breeze.config_store_erase(
            args.key
        ),
        "decision.routes": lambda: breeze.decision_routes(args.node),
        "decision.adj": breeze.decision_adj,
        "decision.prefixes": breeze.decision_prefixes,
        "decision.rib_policy": breeze.decision_rib_policy,
        "fib.routes": breeze.fib_routes,
        "fib.counters": breeze.fib_counters,
        "kvstore.keys": lambda: breeze.kvstore_keys(args.prefix, args.area),
        "kvstore.get_key": lambda: breeze.kvstore_get_key(
            args.key, args.area
        ),
        "kvstore.set_key": lambda: breeze.kvstore_set_key(
            args.key, args.value, args.version, args.area
        ),
        "kvstore.erase_key": lambda: breeze.kvstore_erase_key(
            args.key, args.area
        ),
        "kvstore.peers": lambda: breeze.kvstore_peers(args.area),
        "kvstore.areas": breeze.kvstore_areas,
        "kvstore.flood": lambda: breeze.kvstore_flood(args.area),
        "lm.links": breeze.lm_links,
        "lm.adj": breeze.lm_adj,
        "lm.set_node_overload": breeze.lm_set_node_overload,
        "lm.unset_node_overload": breeze.lm_unset_node_overload,
        "lm.set_link_overload": lambda: breeze.lm_set_link_overload(
            args.interface
        ),
        "lm.unset_link_overload": lambda: breeze.lm_unset_link_overload(
            args.interface
        ),
        "lm.set_link_metric": lambda: breeze.lm_set_link_metric(
            args.interface, args.neighbor, args.metric
        ),
        "lm.unset_link_metric": lambda: breeze.lm_unset_link_metric(
            args.interface, args.neighbor
        ),
        "lm.set_adj_metric": lambda: breeze.lm_set_link_metric(
            args.interface, args.neighbor, args.metric
        ),
        "lm.unset_adj_metric": lambda: breeze.lm_unset_link_metric(
            args.interface, args.neighbor
        ),
        "lm.set_interface_metric": lambda: (
            breeze.lm_set_interface_metric(
                args.interface, args.metric
            )
        ),
        "lm.unset_interface_metric": lambda: (
            breeze.lm_unset_interface_metric(args.interface)
        ),
        "monitor.counters": breeze.monitor_counters,
        "monitor.logs": lambda: breeze.monitor_logs(args.limit),
        "monitor.traces": lambda: breeze.monitor_traces(
            args.limit, args.fmt
        ),
        "monitor.flight": lambda: breeze.monitor_flight(
            args.limit, args.dump, args.fmt
        ),
        "monitor.replay": lambda: breeze.monitor_replay(
            args.bundle, args.as_json, args.backend, args.twice
        ),
        "openr.version": breeze.openr_version,
        "openr.config": breeze.openr_config,
        "perf.fib": breeze.perf_fib,
        "prefixmgr.view": breeze.prefixmgr_view,
        "prefixmgr.advertise": lambda: breeze.prefixmgr_advertise(
            args.prefixes
        ),
        "prefixmgr.withdraw": lambda: breeze.prefixmgr_withdraw(
            args.prefixes
        ),
        "prefixmgr.sync": lambda: breeze.prefixmgr_sync(
            args.prefix_type, args.prefixes
        ),
        "prefixmgr.advertised_routes":
            breeze.prefixmgr_advertised_routes,
        "spark.neighbors": breeze.spark_neighbors,
        "tech_support.": breeze.tech_support,
        "tech_support": breeze.tech_support,
    }
    key = f"{group}.{command}" if command else group
    fn = dispatch.get(key)
    if fn is None:
        print(f"unknown command: {key}", file=sys.stderr)
        return 1
    fn()
    return 0


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()
