"""Table rendering for the breeze CLI (reference analogue:
openr/py/openr/utils/printing.py)."""

from __future__ import annotations

from typing import Iterable, List, Sequence


def render_table(
    headers: Sequence[str], rows: Iterable[Sequence[object]]
) -> str:
    rows = [[str(c) for c in row] for row in rows]
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))
    lines: List[str] = []
    sep = "  "
    lines.append(sep.join(h.ljust(widths[i]) for i, h in enumerate(headers)))
    lines.append(sep.join("-" * w for w in widths))
    for row in rows:
        lines.append(
            sep.join(cell.ljust(widths[i]) for i, cell in enumerate(row))
        )
    return "\n".join(lines)


def caption(text: str) -> str:
    return f"\n> {text}\n"
