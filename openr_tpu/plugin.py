"""Link-time extension point for external route-origination backends.

The reference exposes ``pluginStart(PluginArgs)`` / ``pluginStop()`` as a
default-no-op hook that vendors override at link time (reference:
openr/plugin/Plugin.h:24-34, default impl openr/plugin/Plugin.cpp:11-19,
invoked from Main.cpp:595-601 when BGP peering is enabled). A plugin
receives the prefix-update queue (to originate prefixes), the
static-routes queue (to inject routes into Decision), a reader of
Decision's route updates, and the parsed config.

Python has no link-time substitution, so the hook is a process-wide
registration: call :func:`register_plugin` before the daemon starts.
This is also the registration point for alternate SPF solver backends
(the north-star "TPU solver as a drop-in SpfSolver" shape): see
:func:`openr_tpu.decision.spf_solver.register_spf_backend`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from openr_tpu.messaging.queue import ReplicateQueue, RQueue


@dataclass
class PluginArgs:
    """reference: openr/plugin/Plugin.h:24 PluginArgs."""

    prefix_updates_queue: ReplicateQueue
    static_routes_queue: ReplicateQueue
    route_updates_reader: RQueue
    config: Any = None
    # the resolved BGP peering section (config.bgp_config.BgpConfig) —
    # what a BGP speaker plugin peers from; None when BGP peering is
    # disabled (the reference only calls pluginStart when it is
    # enabled, Main.cpp:595-601)
    bgp_config: Any = None
    ssl_context: Any = None  # parity slot; TLS is handled by ctrl server


_registered_start: Optional[Callable[[PluginArgs], None]] = None
_registered_stop: Optional[Callable[[], None]] = None


def register_plugin(
    start: Callable[[PluginArgs], None],
    stop: Optional[Callable[[], None]] = None,
) -> None:
    """Install the process-wide plugin. Must be called before the daemon
    (OpenrNode) starts; replaces any previous registration."""
    global _registered_start, _registered_stop
    _registered_start = start
    _registered_stop = stop


def unregister_plugin() -> None:
    global _registered_start, _registered_stop
    _registered_start = None
    _registered_stop = None


def has_plugin() -> bool:
    return _registered_start is not None


def plugin_start(args: PluginArgs) -> None:
    """reference: pluginStart — no-op unless a plugin is registered."""
    if _registered_start is not None:
        _registered_start(args)


def plugin_stop() -> None:
    """reference: pluginStop."""
    if _registered_stop is not None:
        _registered_stop()
