"""SLO classes for the solver service's continuous-batching scheduler.

Three classes, inference-server style: ``premium`` rides the front of
every wave (its pending requests preempt lower classes when a wave's
admission budget fills), ``standard`` is the default, and ``bulk``
absorbs whatever slots the higher classes leave vacant. All three
share the 100 ms p99 latency objective for the CPU smoke gate —
the classes differ in *ordering under contention*, not in the target,
so the acceptance check is premium p99 <= standard p99 under a
mixed-class storm rather than absolute numbers per class.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

from openr_tpu.ops.world_batch import SLO_CLASSES, TENANCY_COUNTERS


@dataclass(frozen=True)
class SloClass:
    """One admission class: lower ``priority`` is admitted earlier;
    ``target_p99_ms`` is the per-class latency objective the breach
    triage recipe (RUNBOOK) and the serve smoke gate read."""

    name: str
    priority: int
    target_p99_ms: float


SLO_TABLE: Dict[str, SloClass] = {
    "premium": SloClass("premium", 0, 100.0),
    "standard": SloClass("standard", 1, 100.0),
    "bulk": SloClass("bulk", 2, 100.0),
}

assert tuple(SLO_TABLE) == SLO_CLASSES


def slo_of(name: str) -> SloClass:
    """Class record for ``name``; unknown names are an error (the
    tenant plane enforces the same closed set in ``set_slo_class``)."""
    return SLO_TABLE[name]


def order_requests(
    requests: Sequence[Tuple[str, int]],
) -> List[Tuple[str, int]]:
    """Wave admission order for ``[(slo_name, seq), ...]`` pending
    requests: (class priority, arrival seq). A higher-class request
    placed ahead of an EARLIER-arrived lower-class one is a
    preemption — counted in ``tenancy.wave_preemptions`` so queue
    jumps are never silent."""
    ordered = sorted(
        requests, key=lambda r: (SLO_TABLE[r[0]].priority, r[1])
    )
    preemptions = 0
    for pos, (name, seq) in enumerate(ordered):
        pri = SLO_TABLE[name].priority
        for later in ordered[pos + 1 :]:
            if SLO_TABLE[later[0]].priority > pri and later[1] < seq:
                preemptions += 1
    if preemptions:
        TENANCY_COUNTERS["wave_preemptions"] += preemptions
    return ordered
