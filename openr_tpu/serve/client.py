"""SolverClient: the client daemon's side of solver-as-a-service.

Deliberately *thin and jax-free*: a client process imports only the
stdlib, numpy, and the wire codec — no jax, no graph compiler, no
engines. That is the point of the ownership inversion: many cheap
client daemons (Decision instances, twins, what-if tools) feed worlds
to ONE device-owning service process and read views back.

Speaks the ctrl transport's JSON frames (the same
``{"method", "kwargs"}`` envelope ``CtrlServer`` dual-stacks), so a
solver client and a breeze CLI can share a port. Worlds travel as
base64 ``utils.wire`` AdjacencyDatabase blobs; views come back as
base64 int32 packed blocks decoded into ``SolverView``.
"""

from __future__ import annotations

import base64
import json
import socket
import struct
from typing import Dict, Iterable, List, Optional

import numpy as np

from openr_tpu.types.lsdb import AdjacencyDatabase
from openr_tpu.utils import wire


def _send_frame(sock: socket.socket, obj) -> None:
    payload = json.dumps(obj).encode("utf-8")
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Optional[Dict]:
    header = b""
    while len(header) < 4:
        chunk = sock.recv(4 - len(header))
        if not chunk:
            return None
        header += chunk
    (length,) = struct.unpack(">I", header)
    payload = b""
    while len(payload) < length:
        chunk = sock.recv(length - len(payload))
        if not chunk:
            return None
        payload += chunk
    return json.loads(payload.decode("utf-8"))


class SolverView:
    """Decoded tenant view: ``packed`` is the [2b, n_pad] int32 block
    (rows [0, b) distances per source, rows [b, 2b) first hops — the
    ``ell_view_batch_packed`` layout), ``nodes`` maps column -> node
    name, and row 0 is the root's distance row."""

    def __init__(self, reply: Dict):
        self.root: str = reply["root"]
        self.srcs: List[int] = list(reply["srcs"])
        self.n_pad: int = int(reply["n_pad"])
        self.nodes: List[str] = list(reply["nodes"])
        shape = tuple(reply["shape"])
        self.packed = np.frombuffer(
            base64.b64decode(reply["packed_b64"]), dtype=np.int32
        ).reshape(shape)
        self.index: Dict[str, int] = {
            name: i for i, name in enumerate(self.nodes)
        }

    def distance(self, dst: str) -> int:
        return int(self.packed[0, self.index[dst]])

    def digest(self) -> int:
        """FNV-1a over the packed bytes — what the parity gates
        compare against a server/oracle digest."""
        h = 0x811C9DC5
        for b in self.packed.tobytes():
            h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
        return h


class SolverClient:
    """One TCP connection to a ``SolverService``; every tenant
    registered through it is tied to this connection server-side (a
    disconnect parks them warm)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 2018,
                 timeout_s: float = 120.0):
        self._sock = socket.create_connection(
            (host, port), timeout=timeout_s
        )

    def _call(self, method: str, **kwargs):
        _send_frame(self._sock, {"method": method, "kwargs": kwargs})
        reply = _recv_frame(self._sock)
        if reply is None:
            raise ConnectionError("solver service closed connection")
        if not reply.get("ok"):
            raise RuntimeError(reply.get("error", "unknown error"))
        return reply.get("result")

    # -- surface -----------------------------------------------------------

    def hello(self) -> Dict:
        return self._call("solver_hello")

    def ping(self) -> Dict:
        return self._call("solver_ping")

    def register(self, tenant_id: str, slo: str = "standard",
                 area: str = "0") -> Dict:
        return self._call(
            "solver_register", tenant_id=tenant_id, slo=slo, area=area
        )

    def update_world(
        self,
        tenant_id: str,
        adj_dbs: Iterable[AdjacencyDatabase],
        root: Optional[str] = None,
    ) -> Dict:
        blobs = [
            base64.b64encode(wire.dumps(db)).decode()
            for db in adj_dbs
        ]
        return self._call(
            "solver_update", tenant_id=tenant_id, adj_dbs=blobs,
            root=root,
        )

    def solve(self, tenant_id: str,
              timeout: float = 60.0) -> SolverView:
        return SolverView(self._call(
            "solver_solve", tenant_id=tenant_id, timeout=timeout
        ))

    def ksp2(self, tenant_id: str, dsts: List[str]) -> Dict:
        return self._call(
            "solver_ksp2", tenant_id=tenant_id, dsts=list(dsts)
        )

    def detach(self, tenant_id: str, warm: bool = True) -> Dict:
        return self._call(
            "solver_detach", tenant_id=tenant_id, warm=warm
        )

    def counters(self) -> Dict:
        return self._call("solver_counters")

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
