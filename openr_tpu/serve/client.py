"""SolverClient: the client daemon's side of solver-as-a-service.

Deliberately *thin and jax-free*: a client process imports only the
stdlib, numpy, and the wire codec — no jax, no graph compiler, no
engines. That is the point of the ownership inversion: many cheap
client daemons (Decision instances, twins, what-if tools) feed worlds
to ONE device-owning service process and read views back.

Speaks the ctrl transport's JSON frames (the same
``{"method", "kwargs"}`` envelope ``CtrlServer`` dual-stacks), so a
solver client and a breeze CLI can share a port. Worlds travel as
base64 ``utils.wire`` AdjacencyDatabase blobs; views come back as
base64 int32 packed blocks decoded into ``SolverView``.

Fleet awareness (ISSUE 20): a service restart, a live migration, or a
standby promotion must never surface as a raw socket error or a
silent hang. The call path therefore:

- **reconnects with jittered backoff** (the stock
  ``utils.eventbase.ExponentialBackoff``) when a wire drops, then
  re-registers every tenant routed over it (the service parked them
  warm on disconnect — re-registration reattaches the connection
  binding and the next solve rehydrates warm);
- **follows ``moved_to`` redirects** from a migration seal — the
  per-tenant route table flips to the destination and the call
  retries there, counted in ``self.redirects`` (the server side
  counts ``fleet.client_redirects``);
- **honors retry-later** replies (a tenant frozen mid-drain) by
  sleeping the server's hint instead of failing;
- **falls back to the fleet controller** (``controller=(host,
  port)``) when the cached endpoint stops answering entirely — a
  ``fleet_lookup`` names the tenant's current owner, which also
  covers promotions (the endpoint flips to the adopted standby).
"""

from __future__ import annotations

import base64
import itertools
import json
import os
import socket
import struct
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from openr_tpu.types.lsdb import AdjacencyDatabase, PrefixDatabase
from openr_tpu.types.fib import RouteDatabase
from openr_tpu.utils import wire
from openr_tpu.utils.eventbase import ExponentialBackoff

# distinct trace ids across many clients in one process (the load
# driver spawns several per worker)
_CLIENT_SEQ = itertools.count(1)

Endpoint = Tuple[str, int]


def _send_frame(sock: socket.socket, obj) -> None:
    payload = json.dumps(obj).encode("utf-8")
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Optional[Dict]:
    header = b""
    while len(header) < 4:
        chunk = sock.recv(4 - len(header))
        if not chunk:
            return None
        header += chunk
    (length,) = struct.unpack(">I", header)
    payload = b""
    while len(payload) < length:
        chunk = sock.recv(length - len(payload))
        if not chunk:
            return None
        payload += chunk
    return json.loads(payload.decode("utf-8"))


class SolverView:
    """Decoded tenant view: ``packed`` is the [2b, n_pad] int32 block
    (rows [0, b) distances per source, rows [b, 2b) first hops — the
    ``ell_view_batch_packed`` layout), ``nodes`` maps column -> node
    name, and row 0 is the root's distance row."""

    def __init__(self, reply: Dict):
        self.root: str = reply["root"]
        self.srcs: List[int] = list(reply["srcs"])
        self.n_pad: int = int(reply["n_pad"])
        self.nodes: List[str] = list(reply["nodes"])
        shape = tuple(reply["shape"])
        self.packed = np.frombuffer(
            base64.b64decode(reply["packed_b64"]), dtype=np.int32
        ).reshape(shape)
        self.index: Dict[str, int] = {
            name: i for i, name in enumerate(self.nodes)
        }

    def distance(self, dst: str) -> int:
        return int(self.packed[0, self.index[dst]])

    def digest(self) -> int:
        """FNV-1a over the packed bytes — what the parity gates
        compare against a server/oracle digest."""
        h = 0x811C9DC5
        for b in self.packed.tobytes():
            h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
        return h


class FibView:
    """Decoded FIB-level tenant view: the tenant's full canonical
    ``RouteDatabase`` (unicast + MPLS route products, not just the
    SP distances) plus the server's digest of the same bytes."""

    def __init__(self, reply: Dict):
        self.root: str = reply["root"]
        self.digest: int = int(reply["digest"])
        self.blob: bytes = base64.b64decode(reply["route_db_b64"])
        self.route_db: RouteDatabase = wire.loads(
            self.blob, RouteDatabase
        )

    def unicast_count(self) -> int:
        return len(self.route_db.unicast_routes)

    def mpls_count(self) -> int:
        return len(self.route_db.mpls_routes)


class SolverClient:
    """One client daemon's wire to the solver fleet. Tenants are
    routed per-endpoint (``_route``); every tenant registered through
    an endpoint's connection is tied to it server-side (a disconnect
    parks them warm, re-registration reattaches).

    Cross-wire tracing: every request frame carries a top-level
    ``"trace"`` object (trace id stable per client, span id fresh per
    call) that the service adopts into its wave spans and flight
    records — a client-observed latency anomaly is chaseable to the
    exact service wave that served it. The client also keeps a rolling
    solve-latency window; a p99 breach against its own EWMA baseline
    (``breach_factor`` x, absolute ``breach_floor_ms``) fires a
    service-side ``dump_postmortem`` over the same wire, stamped with
    the breaching span id. Pass ``breach_factor=None`` to disarm."""

    def __init__(self, host: str = "127.0.0.1", port: int = 2018,
                 timeout_s: float = 120.0,
                 breach_factor: Optional[float] = 4.0,
                 breach_min_samples: int = 64,
                 breach_floor_ms: float = 50.0,
                 controller: Optional[Endpoint] = None,
                 backoff_initial_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 max_attempts: int = 64):
        self._default_ep: Endpoint = (host, port)
        self._timeout_s = timeout_s
        self._conns: Dict[Endpoint, socket.socket] = {}
        self._route: Dict[str, Endpoint] = {}
        self._registered: Dict[str, Tuple[str, str]] = {}
        self._controller: Optional[Endpoint] = (
            (str(controller[0]), int(controller[1]))
            if controller is not None else None
        )
        # decorrelated jitter so a fleet of clients hammered off one
        # dead service does not re-dial in lockstep
        self._backoff = ExponentialBackoff(
            backoff_initial_s, backoff_max_s, jitter=True,
            seed=(os.getpid() << 8) ^ id(self) & 0xFF,
        )
        self._max_attempts = max(1, max_attempts)
        self.redirects = 0
        self.reconnects = 0
        # eager dial: constructing a client against a dead endpoint
        # still fails fast (the retry machinery guards LATER drops)
        self._sock_for(self._default_ep)
        self._trace_id = f"sc-{os.getpid():x}-{next(_CLIENT_SEQ):x}"
        self._span_seq = itertools.count(1)
        self.last_span_id: Optional[str] = None
        self.span_ids: deque = deque(maxlen=1024)
        self._breach_factor = breach_factor
        self._breach_min_samples = max(8, int(breach_min_samples))
        self._breach_floor_ms = breach_floor_ms
        self._lat_ring: deque = deque(maxlen=256)
        self._breach_baseline: Optional[float] = None
        self.breaches = 0

    @property
    def trace_id(self) -> str:
        return self._trace_id

    # back-compat shim: the pre-fleet client exposed its single socket
    @property
    def _sock(self) -> socket.socket:
        return self._sock_for(self._default_ep)

    def _next_trace(self, method: str) -> Dict:
        span_id = f"{self._trace_id}.{next(self._span_seq):x}"
        self.last_span_id = span_id
        self.span_ids.append(span_id)
        return {
            "trace_id": self._trace_id,
            "span_id": span_id,
            "origin": "solver_client",
            "method": method,
        }

    # -- wire plumbing -------------------------------------------------

    def _sock_for(self, ep: Endpoint) -> socket.socket:
        sock = self._conns.get(ep)
        if sock is None:
            sock = socket.create_connection(
                ep, timeout=self._timeout_s
            )
            self._conns[ep] = sock
        return sock

    def _drop_conn(self, ep: Endpoint) -> None:
        sock = self._conns.pop(ep, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _raw_call(self, ep: Endpoint, method: str, **kwargs) -> Dict:
        sock = self._sock_for(ep)
        _send_frame(sock, {
            "method": method,
            "kwargs": kwargs,
            "trace": self._next_trace(method),
        })
        reply = _recv_frame(sock)
        if reply is None:
            raise ConnectionError("solver service closed connection")
        return reply

    def _reregister(self, ep: Endpoint) -> None:
        """After a reconnect (or a redirect landing on a fresh wire):
        re-declare every tenant routed to ``ep`` so the service ties
        them to the NEW connection. Parked-warm records rehydrate on
        the next solve; failures here fall through to the main retry
        loop."""
        for tid, route_ep in list(self._route.items()):
            if route_ep != ep:
                continue
            reg = self._registered.get(tid)
            if reg is None:
                continue
            slo, area = reg
            try:
                self._raw_call(
                    ep, "solver_register",
                    tenant_id=tid, slo=slo, area=area,
                )
            except (ConnectionError, OSError):
                return  # wire still bad: the retry loop owns it

    def _relocate(self, tenant_id: Optional[str],
                  ep: Endpoint) -> Endpoint:
        """Endpoint lost and no redirect in hand: ask the fleet
        controller who owns the tenant now (covers migrations sealed
        while we were gone AND standby promotions, where the old
        primary simply vanishes)."""
        if tenant_id is None or self._controller is None:
            return ep
        try:
            reply = self._raw_call(
                self._controller, "fleet_lookup", tenant_id=tenant_id
            )
        except (ConnectionError, OSError):
            self._drop_conn(self._controller)
            return ep
        if not reply.get("ok"):
            return ep
        result = reply.get("result") or {}
        new_ep = (str(result["host"]), int(result["port"]))
        if new_ep != ep:
            self.redirects += 1
            self._route[tenant_id] = new_ep
            self._reregister(new_ep)
        return new_ep

    def _call(self, method: str, _tenant: Optional[str] = None,
              **kwargs):
        ep = (
            self._route.get(_tenant, self._default_ep)
            if _tenant is not None else self._default_ep
        )
        last_exc: Optional[BaseException] = None
        for _attempt in range(self._max_attempts):
            try:
                reply = self._raw_call(ep, method, **kwargs)
            except (ConnectionError, OSError) as exc:
                last_exc = exc
                self._drop_conn(ep)
                self.reconnects += 1
                self._backoff.report_error()
                delay = (
                    self._backoff.get_time_remaining_until_retry()
                )
                if delay > 0:
                    time.sleep(min(delay, 1.0))
                relocated = self._relocate(_tenant, ep)
                if relocated == ep:
                    # same endpoint: re-dial + re-register happens on
                    # the next _raw_call / after it succeeds
                    try:
                        self._sock_for(ep)
                        self._reregister(ep)
                    except (ConnectionError, OSError):
                        pass
                ep = relocated
                continue
            if reply.get("ok"):
                self._backoff.report_success()
                if _tenant is not None:
                    self._route[_tenant] = ep
                return reply.get("result")
            moved = reply.get("moved_to")
            if isinstance(moved, dict):
                # migration seal: chase the tenant to its new owner
                new_ep = (str(moved["host"]), int(moved["port"]))
                self.redirects += 1
                if _tenant is not None:
                    self._route[_tenant] = new_ep
                    self._reregister(new_ep)
                ep = new_ep
                continue
            if reply.get("retry"):
                # frozen mid-migration: honor the server's hint
                time.sleep(max(
                    0.001,
                    float(reply.get("retry_after_ms", 50.0)) / 1000.0,
                ))
                continue
            raise RuntimeError(reply.get("error", "unknown error"))
        if last_exc is not None:
            raise ConnectionError(
                f"{method}: retries exhausted ({last_exc})"
            ) from last_exc
        raise ConnectionError(f"{method}: retries exhausted")

    # -- client-observed p99 breach watch ------------------------------

    def _observe_solve_latency(self, ms: float) -> None:
        if self._breach_factor is None:
            return
        self._lat_ring.append(ms)
        if len(self._lat_ring) < self._breach_min_samples:
            return
        ordered = sorted(self._lat_ring)
        p99 = ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]
        if self._breach_baseline is None:
            self._breach_baseline = p99
            return
        baseline = self._breach_baseline
        threshold = max(self._breach_floor_ms,
                        self._breach_factor * baseline)
        self._breach_baseline = 0.9 * baseline + 0.1 * p99
        if p99 > threshold:
            # re-baseline: one sustained regression fires once, and the
            # service-side rate limiter bounds a fleet of clients
            self._breach_baseline = p99
            self.breaches += 1
            try:
                self.dump_postmortem(
                    trigger="client_p99_breach",
                    reason=(f"client-observed p99 {p99:.2f}ms > "
                            f"{self._breach_factor:g}x baseline "
                            f"{baseline:.2f}ms; trace {self.last_span_id}"),
                )
            except (RuntimeError, ConnectionError, OSError):
                pass  # a breach report must never break the solve path

    # -- surface -----------------------------------------------------------

    def hello(self) -> Dict:
        return self._call("solver_hello")

    def ping(self) -> Dict:
        return self._call("solver_ping")

    def register(self, tenant_id: str, slo: str = "standard",
                 area: str = "0") -> Dict:
        self._registered[tenant_id] = (slo, area)
        self._route.setdefault(tenant_id, self._default_ep)
        return self._call(
            "solver_register", _tenant=tenant_id,
            tenant_id=tenant_id, slo=slo, area=area,
        )

    def update_world(
        self,
        tenant_id: str,
        adj_dbs: Iterable[AdjacencyDatabase],
        root: Optional[str] = None,
        prefix_dbs: Optional[Iterable[PrefixDatabase]] = None,
    ) -> Dict:
        blobs = [
            base64.b64encode(wire.dumps(db)).decode()
            for db in adj_dbs
        ]
        prefix_blobs = [
            base64.b64encode(wire.dumps(db)).decode()
            for db in (prefix_dbs or [])
        ]
        return self._call(
            "solver_update", _tenant=tenant_id,
            tenant_id=tenant_id, adj_dbs=blobs, root=root,
            prefix_dbs=prefix_blobs or None,
        )

    def solve(self, tenant_id: str,
              timeout: float = 60.0) -> SolverView:
        t0 = time.perf_counter()
        view = SolverView(self._call(
            "solver_solve", _tenant=tenant_id,
            tenant_id=tenant_id, timeout=timeout,
        ))
        self._observe_solve_latency((time.perf_counter() - t0) * 1000.0)
        return view

    def fib(self, tenant_id: str, timeout: float = 60.0) -> FibView:
        """The tenant's full route product (``RouteDatabase``), not
        just the SP view — decoded jax-free off the wire."""
        return FibView(self._call(
            "solver_fib", _tenant=tenant_id,
            tenant_id=tenant_id, timeout=timeout,
        ))

    def ksp2(self, tenant_id: str, dsts: List[str]) -> Dict:
        return self._call(
            "solver_ksp2", _tenant=tenant_id,
            tenant_id=tenant_id, dsts=list(dsts),
        )

    def detach(self, tenant_id: str, warm: bool = True) -> Dict:
        return self._call(
            "solver_detach", _tenant=tenant_id,
            tenant_id=tenant_id, warm=warm,
        )

    def endpoint_of(self, tenant_id: str) -> Endpoint:
        """Where this client currently routes the tenant (tests +
        tooling introspection)."""
        return self._route.get(tenant_id, self._default_ep)

    def counters(self) -> Dict:
        return self._call("solver_counters")

    def dump_postmortem(self, trigger: str = "manual",
                        reason: str = "") -> Dict:
        """Ask the SERVICE to cut a post-mortem bundle (the breach
        watch calls this with the breaching trace stamped into the
        reason, so the bundle pairs with the client's observation)."""
        return self._call(
            "dump_postmortem", trigger=trigger, reason=reason
        )

    def close(self) -> None:
        for ep in list(self._conns):
            self._drop_conn(ep)
