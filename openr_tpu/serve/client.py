"""SolverClient: the client daemon's side of solver-as-a-service.

Deliberately *thin and jax-free*: a client process imports only the
stdlib, numpy, and the wire codec — no jax, no graph compiler, no
engines. That is the point of the ownership inversion: many cheap
client daemons (Decision instances, twins, what-if tools) feed worlds
to ONE device-owning service process and read views back.

Speaks the ctrl transport's JSON frames (the same
``{"method", "kwargs"}`` envelope ``CtrlServer`` dual-stacks), so a
solver client and a breeze CLI can share a port. Worlds travel as
base64 ``utils.wire`` AdjacencyDatabase blobs; views come back as
base64 int32 packed blocks decoded into ``SolverView``.
"""

from __future__ import annotations

import base64
import itertools
import json
import os
import socket
import struct
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

import numpy as np

from openr_tpu.types.lsdb import AdjacencyDatabase
from openr_tpu.utils import wire

# distinct trace ids across many clients in one process (the load
# driver spawns several per worker)
_CLIENT_SEQ = itertools.count(1)


def _send_frame(sock: socket.socket, obj) -> None:
    payload = json.dumps(obj).encode("utf-8")
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Optional[Dict]:
    header = b""
    while len(header) < 4:
        chunk = sock.recv(4 - len(header))
        if not chunk:
            return None
        header += chunk
    (length,) = struct.unpack(">I", header)
    payload = b""
    while len(payload) < length:
        chunk = sock.recv(length - len(payload))
        if not chunk:
            return None
        payload += chunk
    return json.loads(payload.decode("utf-8"))


class SolverView:
    """Decoded tenant view: ``packed`` is the [2b, n_pad] int32 block
    (rows [0, b) distances per source, rows [b, 2b) first hops — the
    ``ell_view_batch_packed`` layout), ``nodes`` maps column -> node
    name, and row 0 is the root's distance row."""

    def __init__(self, reply: Dict):
        self.root: str = reply["root"]
        self.srcs: List[int] = list(reply["srcs"])
        self.n_pad: int = int(reply["n_pad"])
        self.nodes: List[str] = list(reply["nodes"])
        shape = tuple(reply["shape"])
        self.packed = np.frombuffer(
            base64.b64decode(reply["packed_b64"]), dtype=np.int32
        ).reshape(shape)
        self.index: Dict[str, int] = {
            name: i for i, name in enumerate(self.nodes)
        }

    def distance(self, dst: str) -> int:
        return int(self.packed[0, self.index[dst]])

    def digest(self) -> int:
        """FNV-1a over the packed bytes — what the parity gates
        compare against a server/oracle digest."""
        h = 0x811C9DC5
        for b in self.packed.tobytes():
            h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
        return h


class SolverClient:
    """One TCP connection to a ``SolverService``; every tenant
    registered through it is tied to this connection server-side (a
    disconnect parks them warm).

    Cross-wire tracing: every request frame carries a top-level
    ``"trace"`` object (trace id stable per client, span id fresh per
    call) that the service adopts into its wave spans and flight
    records — a client-observed latency anomaly is chaseable to the
    exact service wave that served it. The client also keeps a rolling
    solve-latency window; a p99 breach against its own EWMA baseline
    (``breach_factor`` x, absolute ``breach_floor_ms``) fires a
    service-side ``dump_postmortem`` over the same wire, stamped with
    the breaching span id. Pass ``breach_factor=None`` to disarm."""

    def __init__(self, host: str = "127.0.0.1", port: int = 2018,
                 timeout_s: float = 120.0,
                 breach_factor: Optional[float] = 4.0,
                 breach_min_samples: int = 64,
                 breach_floor_ms: float = 50.0):
        self._sock = socket.create_connection(
            (host, port), timeout=timeout_s
        )
        self._trace_id = f"sc-{os.getpid():x}-{next(_CLIENT_SEQ):x}"
        self._span_seq = itertools.count(1)
        self.last_span_id: Optional[str] = None
        self.span_ids: deque = deque(maxlen=1024)
        self._breach_factor = breach_factor
        self._breach_min_samples = max(8, int(breach_min_samples))
        self._breach_floor_ms = breach_floor_ms
        self._lat_ring: deque = deque(maxlen=256)
        self._breach_baseline: Optional[float] = None
        self.breaches = 0

    @property
    def trace_id(self) -> str:
        return self._trace_id

    def _next_trace(self, method: str) -> Dict:
        span_id = f"{self._trace_id}.{next(self._span_seq):x}"
        self.last_span_id = span_id
        self.span_ids.append(span_id)
        return {
            "trace_id": self._trace_id,
            "span_id": span_id,
            "origin": "solver_client",
            "method": method,
        }

    def _call(self, method: str, **kwargs):
        _send_frame(self._sock, {
            "method": method,
            "kwargs": kwargs,
            "trace": self._next_trace(method),
        })
        reply = _recv_frame(self._sock)
        if reply is None:
            raise ConnectionError("solver service closed connection")
        if not reply.get("ok"):
            raise RuntimeError(reply.get("error", "unknown error"))
        return reply.get("result")

    # -- client-observed p99 breach watch ------------------------------

    def _observe_solve_latency(self, ms: float) -> None:
        if self._breach_factor is None:
            return
        self._lat_ring.append(ms)
        if len(self._lat_ring) < self._breach_min_samples:
            return
        ordered = sorted(self._lat_ring)
        p99 = ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]
        if self._breach_baseline is None:
            self._breach_baseline = p99
            return
        baseline = self._breach_baseline
        threshold = max(self._breach_floor_ms,
                        self._breach_factor * baseline)
        self._breach_baseline = 0.9 * baseline + 0.1 * p99
        if p99 > threshold:
            # re-baseline: one sustained regression fires once, and the
            # service-side rate limiter bounds a fleet of clients
            self._breach_baseline = p99
            self.breaches += 1
            try:
                self.dump_postmortem(
                    trigger="client_p99_breach",
                    reason=(f"client-observed p99 {p99:.2f}ms > "
                            f"{self._breach_factor:g}x baseline "
                            f"{baseline:.2f}ms; trace {self.last_span_id}"),
                )
            except (RuntimeError, ConnectionError, OSError):
                pass  # a breach report must never break the solve path

    # -- surface -----------------------------------------------------------

    def hello(self) -> Dict:
        return self._call("solver_hello")

    def ping(self) -> Dict:
        return self._call("solver_ping")

    def register(self, tenant_id: str, slo: str = "standard",
                 area: str = "0") -> Dict:
        return self._call(
            "solver_register", tenant_id=tenant_id, slo=slo, area=area
        )

    def update_world(
        self,
        tenant_id: str,
        adj_dbs: Iterable[AdjacencyDatabase],
        root: Optional[str] = None,
    ) -> Dict:
        blobs = [
            base64.b64encode(wire.dumps(db)).decode()
            for db in adj_dbs
        ]
        return self._call(
            "solver_update", tenant_id=tenant_id, adj_dbs=blobs,
            root=root,
        )

    def solve(self, tenant_id: str,
              timeout: float = 60.0) -> SolverView:
        t0 = time.perf_counter()
        view = SolverView(self._call(
            "solver_solve", tenant_id=tenant_id, timeout=timeout
        ))
        self._observe_solve_latency((time.perf_counter() - t0) * 1000.0)
        return view

    def ksp2(self, tenant_id: str, dsts: List[str]) -> Dict:
        return self._call(
            "solver_ksp2", tenant_id=tenant_id, dsts=list(dsts)
        )

    def detach(self, tenant_id: str, warm: bool = True) -> Dict:
        return self._call(
            "solver_detach", tenant_id=tenant_id, warm=warm
        )

    def counters(self) -> Dict:
        return self._call("solver_counters")

    def dump_postmortem(self, trigger: str = "manual",
                        reason: str = "") -> Dict:
        """Ask the SERVICE to cut a post-mortem bundle (the breach
        watch calls this with the breaching trace stamped into the
        reason, so the bundle pairs with the client's observation)."""
        return self._call(
            "dump_postmortem", trigger=trigger, reason=reason
        )

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
