"""SolverService: the device-owning solver process.

Ownership inversion over the rest of the tree: everywhere else the
Decision instance owns its engines and the device; here a standalone
serving process owns ONE private ``WorldManager`` (and through it the
device blocks) and many client daemons talk to it over the ctrl
transport. The scheduler is continuous batching as practiced by
inference servers, mapped onto the tenant plane:

- **Wave loop.** One background thread drains the pending-request
  table into bucket *waves*: each wave syncs + solves every admitted
  tenant in as few fused ``world_dispatch`` calls as the shape buckets
  allow (``WorldManager.solve_views``). Requests that arrive while a
  wave is in flight join the NEXT wave (``tenancy.wave_joins``) — the
  zero-retrace bucket-join contract makes that join free of compiles,
  which is what makes mid-flight joining worth doing at all.

- **SLO classes.** Every tenant carries a class (``premium`` /
  ``standard`` / ``bulk``, serve/slo.py). Wave admission orders
  pending requests by (class priority, arrival seq) and cuts at the
  wave budget: a premium request arriving late still rides the next
  wave ahead of earlier bulk arrivals (counted in
  ``tenancy.wave_preemptions``), and bulk requests absorb whatever
  budget the higher classes leave (they are never starved outright —
  the cut is a budget, not a filter, so leftover bulk rides the
  following wave).

- **Occupancy-sized dispatch.** After waves settle, buckets whose
  vacancy exceeds ~50% are compacted to the power-of-two width that
  fits their occupants (``WorldManager.compact_buckets``) so a
  half-empty fleet stops paying full-width solves.

- **Fault seams.** ``serve.client_disconnect`` fires at result
  delivery: a vanished client's tenants are parked WARM (slot freed,
  mirror + journal kept — the bucket is never poisoned and a
  reconnect rehydrates). ``serve.slow_client`` fires on the ctrl
  reply path (ctrl/solver.py), stalling only that client's connection
  thread, never the wave loop.

Telemetry: ``serve.requests`` / ``serve.waves`` / ``serve.errors`` /
``serve.disconnect_detaches`` counters, ``serve.latency_ms.<class>``
per-class histograms (p99 drives the SLO gate), plus the tenancy
counters the wave loop feeds (wave_joins / wave_preemptions /
wave_occupancy / bucket_compactions).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Set

from openr_tpu.analysis.annotations import flight_callback
from openr_tpu.faults import (
    FaultInjected,
    fault_point,
    register_fault_site,
)
from openr_tpu.ops.world_batch import TENANCY_COUNTERS, WorldManager
from openr_tpu.serve.slo import SLO_TABLE, order_requests
from openr_tpu.telemetry import (
    P99BreachTrigger,
    get_flight_recorder,
    get_profiler,
    get_registry as _get_registry,
    get_tracer,
    install_default_triggers,
)

FAULT_CLIENT_DISCONNECT = register_fault_site("serve.client_disconnect")
FAULT_SLOW_CLIENT = register_fault_site("serve.slow_client")


class SolveRequest:
    """One pending tenant solve: latest-wins per tenant (a newer
    request for the same tenant supersedes the queued one — the solve
    always runs against the tenant's CURRENT LinkState, so coalescing
    is free), delivered through an event the caller blocks on."""

    __slots__ = (
        "tenant_id", "ls", "root", "slo", "seq", "enqueued",
        "event", "view", "error", "superseded", "trace_ctx",
    )

    def __init__(self, tenant_id: str, ls, root: str, slo: str,
                 seq: int, trace_ctx: Optional[Dict] = None):
        self.tenant_id = tenant_id
        self.ls = ls
        self.root = root
        self.slo = slo
        self.seq = seq
        # client-stamped trace context off the wire ({"trace_id",
        # "span_id", ...}): adopted into the wave span + flight record
        # that serve this request, closing the cross-wire trace
        self.trace_ctx = trace_ctx
        self.enqueued = time.perf_counter()
        self.event = threading.Event()
        self.view = None
        self.error: Optional[BaseException] = None
        # waiters on requests this one coalesced over: they are served
        # with THIS request's result (the wave solves the tenant's
        # current world, which answers every superseded ask)
        self.superseded: List["SolveRequest"] = []

    def deliver(self, view=None,
                error: Optional[BaseException] = None) -> None:
        for r in [self] + self.superseded:
            r.view = view
            r.error = error
            r.event.set()

    def wait(self, timeout: float = 60.0):
        if not self.event.wait(timeout):
            raise TimeoutError(
                f"solve({self.tenant_id!r}) not served in {timeout}s"
            )
        if self.error is not None:
            raise self.error
        return self.view


class SolverService:
    """The serving process's core (transport-free; ctrl/solver.py puts
    it behind the wire). Thread model: ctrl connection threads enqueue
    requests and block on their events; ONE wave thread owns every
    WorldManager mutation (the manager is not thread-safe), with
    ``_mgr_lock`` serializing the few out-of-wave touches (register /
    detach / ksp2 view)."""

    def __init__(
        self,
        manager: Optional[WorldManager] = None,
        wave_budget: Optional[int] = None,
        compaction_vacancy: float = 0.5,
        compact_every: int = 16,
    ):
        # PRIVATE manager by default: the service owns the device; it
        # deliberately does not share get_world_manager()'s process
        # singleton with an in-process Decision
        self._mgr = manager if manager is not None else WorldManager()
        self._wave_budget = (
            wave_budget
            if wave_budget is not None
            else 4 * self._mgr.slots_per_bucket
        )
        self._compaction_vacancy = compaction_vacancy
        # consecutive idle wait ticks (~50 ms each) with no pending
        # work before an occupancy-compaction pass may run
        self._compact_every = max(1, compact_every)
        self._placements_at_check = TENANCY_COUNTERS["placements"]
        self._cv = threading.Condition()
        self._pending: Dict[str, SolveRequest] = {}
        self._seq = 0
        self._stop = False
        self._wave_active = False
        self._waves = 0
        self._mgr_lock = threading.RLock()
        self._conn_tenants: Dict[int, Set[str]] = {}
        self._detached: Set[str] = set()
        # SLO classes mirrored under _cv so the request arrival path
        # never touches _mgr_lock — the wave loop holds that for the
        # whole solve, and an arrival blocking on it would serialize
        # behind the wave instead of joining the next one
        self._slo: Dict[str, str] = {}
        self._reg = _get_registry()
        # standing anomaly set + one p99-breach trigger per SLO class,
        # so every breach freezes the flight ring with the admission /
        # window records that explain it (idempotent across services
        # sharing the process recorder)
        fr = install_default_triggers()
        armed = set(fr.trigger_names())
        for cls in SLO_TABLE:
            name = f"p99_breach_{cls}"
            if name not in armed:
                fr.add_trigger(
                    P99BreachTrigger(name, f"serve.latency_ms.{cls}")
                )
        self._thread = threading.Thread(
            target=self._wave_loop, name="solver-wave-loop", daemon=True
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SolverService":
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=10)
        # fail pending waiters rather than hanging their clients
        with self._cv:
            pending = list(self._pending.values())
            self._pending = {}
        for r in pending:
            r.deliver(error=RuntimeError("solver service stopped"))

    @property
    def manager(self) -> WorldManager:
        return self._mgr

    def waves(self) -> int:
        with self._cv:
            return self._waves

    # -- client surface ----------------------------------------------------

    def register(self, tenant_id: str, slo: str = "standard",
                 conn: Optional[int] = None) -> None:
        """Declare a tenant and its SLO class; ``conn`` ties it to a
        ctrl connection so a disconnect detaches it warm."""
        if slo not in SLO_TABLE:
            raise ValueError(f"unknown SLO class: {slo!r}")
        with self._mgr_lock:
            self._mgr.set_slo_class(tenant_id, slo)
        if conn is not None:
            with self._cv:
                self._conn_tenants.setdefault(conn, set()).add(
                    tenant_id
                )
        with self._cv:
            self._slo[tenant_id] = slo
            self._detached.discard(tenant_id)

    def request_solve(self, tenant_id: str, ls, root: str,
                      trace_ctx: Optional[Dict] = None) -> SolveRequest:
        """Enqueue (or supersede) the tenant's pending solve; returns
        the request whose ``wait()`` yields the view. Arrivals during
        an in-flight wave are the continuous-batching case — they ride
        the next wave, counted as wave joins."""
        with self._cv:
            slo = self._slo.get(tenant_id, "standard")
            self._seq += 1
            r = SolveRequest(
                tenant_id, ls, root,
                slo, self._seq,
                trace_ctx=trace_ctx,
            )
            old = self._pending.get(tenant_id)
            if old is not None:
                # latest-wins coalescing: the superseded waiters are
                # served with this wave's view of the same tenant
                r.superseded = old.superseded + [old]
                old.superseded = []
            if self._wave_active:
                TENANCY_COUNTERS["wave_joins"] += 1
                self._reg.counter_bump("serve.wave_joins")
            self._pending[tenant_id] = r
            self._reg.counter_bump("serve.requests")
            self._cv.notify()
        return r

    def solve(self, tenant_id: str, ls, root: str,
              timeout: float = 60.0,
              trace_ctx: Optional[Dict] = None):
        """Blocking convenience wrapper: enqueue + wait for the wave."""
        return self.request_solve(
            tenant_id, ls, root, trace_ctx=trace_ctx
        ).wait(timeout)

    def ksp2(self, tenant_id: str, dsts: Sequence[str]):
        """Second-path view for a solved tenant (the tenant plane's
        ``ksp2_view`` behind the service lock)."""
        with self._mgr_lock:
            return self._mgr.ksp2_view(tenant_id, dsts)

    def detach(self, tenant_id: str, warm: bool = True) -> None:
        """Release a tenant's device slot; ``warm`` keeps the host
        record for a cheap rehydration on return."""
        with self._cv:
            r = self._pending.pop(tenant_id, None)
        if r is not None:
            r.deliver(
                error=RuntimeError(f"tenant {tenant_id!r} detached")
            )
        with self._mgr_lock:
            if warm:
                self._mgr.park(tenant_id)
            else:
                self._mgr.drop(tenant_id)
        with self._cv:
            self._detached.add(tenant_id)

    # -- fleet plane: drain / export / import ------------------------------

    def quiesce(self, tenant_id: str, timeout_s: float = 30.0) -> None:
        """Migration drain barrier: block until the tenant has no
        pending request AND no wave is in flight. After this returns
        (and until the caller re-admits work for the tenant) its host
        record is stable — safe to export. New requests arriving after
        the barrier are the ctrl layer's problem: it freezes the
        tenant (retry-later replies) before draining."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while tenant_id in self._pending or self._wave_active:
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"quiesce({tenant_id!r}) not drained in "
                        f"{timeout_s}s"
                    )
                self._cv.wait(0.02)

    def export_tenant(self, tenant_id: str) -> Dict[str, object]:
        """Serialize the tenant's host record for live migration
        (``WorldManager.export_tenant`` behind the service lock). The
        caller drains first (``quiesce``)."""
        with self._mgr_lock:
            return self._mgr.export_tenant(tenant_id)

    def import_tenant(self, ls, record: Dict[str, object]):
        """Rehydrate a migrated tenant's record against ``ls``
        (``WorldManager.import_tenant`` behind the service lock);
        returns the placed ``TenantWorld``. The first post-import
        solve is warm — zero compiles, zero cold solves — unless the
        record degraded to a counted cold admission."""
        with self._mgr_lock:
            t = self._mgr.import_tenant(ls, record)
        slo = record.get("slo")
        if isinstance(slo, str):
            with self._cv:
                self._slo[str(record["tenant_id"])] = slo
                self._detached.discard(str(record["tenant_id"]))
        return t

    def connection_closed(self, conn: int) -> None:
        """Ctrl-transport teardown hook: every tenant the connection
        registered is parked warm — the shared bucket keeps serving
        its other tenants and a reconnecting client rehydrates."""
        with self._cv:
            tenants = self._conn_tenants.pop(conn, set())
        for tid in tenants:
            self.detach(tid, warm=True)
            self._reg.counter_bump("serve.disconnect_detaches")

    # -- wave loop ---------------------------------------------------------

    def _admit_locked(self) -> List[SolveRequest]:
        """Cut the next wave from the pending table under ``_cv``:
        SLO-ordered, budget-capped. Leftovers stay pending and lead
        the next wave (their seq keeps their place in class order)."""
        by_tenant = dict(self._pending)
        preempt0 = TENANCY_COUNTERS["wave_preemptions"]
        ordered = order_requests(
            [(r.slo, r.seq) for r in by_tenant.values()]
        )
        seq_to_req = {r.seq: r for r in by_tenant.values()}
        admitted = [
            seq_to_req[seq]
            for _cls, seq in ordered[: self._wave_budget]
        ]
        for r in admitted:
            del self._pending[r.tenant_id]
        mix: Dict[str, int] = {}
        for r in admitted:
            mix[r.slo] = mix.get(r.slo, 0) + 1
        get_flight_recorder().note(
            "admission",
            admitted=len(admitted),
            deferred=len(by_tenant) - len(admitted),
            mix=mix,
            preemptions=TENANCY_COUNTERS["wave_preemptions"] - preempt0,
        )
        return admitted

    def _wave_loop(self) -> None:
        idle_ticks = 0
        while True:
            compact = False
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait(0.05)
                    if not self._pending and not self._stop:
                        idle_ticks += 1
                        if idle_ticks >= self._compact_every:
                            idle_ticks = 0
                            compact = True
                            break
                if self._stop:
                    return
                if not compact:
                    idle_ticks = 0
                    batches = [self._admit_locked()]
                    if self._pending:
                        # back-pressure burst: more requests than one
                        # wave's budget. Cut the follow-on wave NOW and
                        # pipeline it behind this one — wave N+1's
                        # bucket dispatches submit before wave N's
                        # readbacks reap (solve_views_pipelined), so
                        # the burst drains at pipeline depth 2 instead
                        # of paying a full host turnaround per wave.
                        batches.append(self._admit_locked())
                    self._wave_active = True
            if compact:
                self._maybe_compact()
                continue
            try:
                self._run_waves(batches)
            finally:
                with self._cv:
                    self._wave_active = False

    def _maybe_compact(self) -> None:
        """Idle-time occupancy compaction. Runs ONLY when the service
        has had no pending work for a stretch AND no placement landed
        since the last check: a resize is a new dispatch width (a
        retrace), so compacting while requests flow — or mid
        admission-ramp, when occupancy lags the tenant count — would
        shrink a bucket that immediately regrows and break the
        zero-compile wave-join contract. Under load the loop never
        enters this branch; the vacancy threshold inside
        ``compact_buckets`` keeps a busy full fleet untouched even
        when it does."""
        placements = TENANCY_COUNTERS["placements"]
        if placements == self._placements_at_check:
            with self._mgr_lock:
                self._mgr.compact_buckets(self._compaction_vacancy)
        self._placements_at_check = placements

    def _run_wave(self, batch: List[SolveRequest]) -> None:
        self._run_waves([batch])

    def _run_waves(self, batches: List[List[SolveRequest]]) -> None:
        """Solve one or more admitted waves — two or more ride the
        tenant plane's pipelined front end, where wave N+1's dispatches
        are submitted before wave N's readbacks land — then deliver
        every request. Failures are relayed per request, never thrown
        at the wave loop.

        Cross-wire tracing: requests carrying a client-stamped trace
        context get their span ids adopted into this wave's service
        span and flight record, so a client-side p99 breach bundle and
        the service wave that served it share ids."""
        client_spans = [
            r.trace_ctx["span_id"]
            for b in batches
            for r in b
            if isinstance(r.trace_ctx, dict) and r.trace_ctx.get("span_id")
        ]
        views_list: Optional[List[List]] = None
        errors = None
        tracer = get_tracer()
        trace = tracer.start(origin="serve.wave")
        tracer.activate(trace)
        span = tracer.span_active("serve.wave_solve")
        try:
            with self._mgr_lock:
                if len(batches) == 1:
                    views_list = [
                        self._mgr.solve_views(
                            [(r.tenant_id, r.ls, r.root)
                             for r in batches[0]]
                        )
                    ]
                else:
                    views_list = self._mgr.solve_views_pipelined(
                        [
                            [(r.tenant_id, r.ls, r.root) for r in b]
                            for b in batches
                        ]
                    )
                    self._reg.counter_bump("serve.pipelined_waves")
        except Exception as exc:  # noqa: BLE001 - relayed per request
            errors = exc
            self._reg.counter_bump("serve.errors")
        finally:
            tracer.end_span_active(
                span,
                waves=len(batches),
                requests=sum(len(b) for b in batches),
                client_spans=client_spans[:64],
            )
            tracer.deactivate()
            tracer.finish(trace)
        get_flight_recorder().note(
            "wave",
            batches=len(batches),
            requests=sum(len(b) for b in batches),
            failed=errors is not None,
            client_spans=client_spans[:64],
        )
        now = time.perf_counter()
        with self._cv:
            self._waves += len(batches)
        for bi, batch in enumerate(batches):
            self._reg.counter_bump("serve.waves")
            views = views_list[bi] if views_list is not None else None
            for i, r in enumerate(batch):
                if errors is not None:
                    r.deliver(error=errors)
                    continue
                try:
                    # the disconnect seam sits AT delivery: the wave
                    # solved this tenant, but its client died before
                    # consuming — park it warm, never poison the bucket
                    fault_point(FAULT_CLIENT_DISCONNECT)
                except FaultInjected:
                    self.detach(r.tenant_id, warm=True)
                    self._reg.counter_bump("serve.disconnect_detaches")
                    r.deliver(error=ConnectionError(
                        f"client of {r.tenant_id!r} disconnected"
                    ))
                    continue
                self._reg.observe(
                    f"serve.latency_ms.{r.slo}",
                    (now - r.enqueued) * 1000.0,
                )
                r.deliver(view=views[i])
        self._check_slo_triggers()

    @flight_callback
    def _check_slo_triggers(self) -> None:
        """Post-delivery anomaly sweep on the wave loop: per-class p99
        breach + the standing trigger set. Runs after every wave, after
        results are delivered and outside any event window, so a
        trigger firing here dumps immediately instead of deferring."""
        get_flight_recorder().check_triggers()

    # -- introspection -----------------------------------------------------

    def class_p99(self, slo: str) -> float:
        return self._reg.percentile(f"serve.latency_ms.{slo}", 0.99)

    def stage_attribution(self) -> Dict[str, object]:
        """Every SLO-class p99 next to the measured per-stage device /
        host costs that produced it — the serve plane's answer to
        'which stage is eating my latency budget'."""
        prof = get_profiler()
        return {
            "class_p99_ms": {
                cls: round(self.class_p99(cls), 3) for cls in SLO_TABLE
            },
            "stages": prof.attribution(),
            "host_overhead_ratio": prof.host_overhead_ratio(),
        }

    def counters(self) -> Dict[str, float]:
        snap = self._reg.snapshot()
        return {
            k: v
            for k, v in snap.items()
            if k.startswith("serve.") or k.startswith("tenancy.")
        }
