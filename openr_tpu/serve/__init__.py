"""Solver-as-a-service: a standalone device-owning solver process
serving many client daemons with continuous batching and SLO classes.

- ``serve.service`` — the ``SolverService`` scheduler + wave loop
  (imports jax through the tenant plane; server-side only).
- ``serve.client`` — the jax-free ``SolverClient`` daemons use.
- ``serve.slo`` — the SLO class table and wave admission ordering.

Import submodules directly (``from openr_tpu.serve.client import
SolverClient``): this package ``__init__`` stays empty of imports so
client processes never pull jax in by accident.
"""
