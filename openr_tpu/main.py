"""openr-tpu daemon entry point.

The analogue of the reference's ``openr/Main.cpp`` main(): parse config
(JSON file via --config, or legacy flags), assemble the module graph,
start the ctrl server and watchdog, run until SIGINT/SIGTERM, tear down
in reverse order.

Run:  python -m openr_tpu.main --config node.json
      python -m openr_tpu.main --node-name fc001 --ifaces eth0,eth1
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from openr_tpu.config.config import OpenrConfig
from openr_tpu.daemon import OpenrNode
from openr_tpu.monitor.watchdog import Watchdog
from openr_tpu.spark.io_provider import UdpIoProvider


def parse_args(argv):
    parser = argparse.ArgumentParser(prog="openr-tpu")
    parser.add_argument("--config", help="JSON config file")
    # legacy flag surface (reference: 99 gflags in common/Flags.cpp;
    # the load-bearing subset)
    parser.add_argument("--node-name", default=None)
    parser.add_argument("--areas", default="0")
    parser.add_argument("--ifaces", default="", help="comma separated")
    parser.add_argument("--ctrl-port", type=int, default=2018)
    parser.add_argument("--dryrun", action="store_true")
    parser.add_argument("--enable-v4", action="store_true")
    parser.add_argument("--use-rtt-metric", action="store_true")
    parser.add_argument("--solver-backend", default="device",
                        choices=["device", "host"])
    parser.add_argument(
        "--enable-netlink-fib", action="store_true",
        help="program routes into the kernel via an in-process "
             "NetlinkFibHandler over rtnetlink (reference: "
             "Main.cpp:343-361)",
    )
    parser.add_argument(
        "--fib-agent-port", type=int, default=0,
        help="connect to an out-of-process platform agent "
             "(python -m openr_tpu.platform.agent) instead",
    )
    parser.add_argument("--spark-port", type=int, default=6666)
    parser.add_argument("-v", "--verbose", action="count", default=0)
    return parser.parse_args(argv)


def build_config(args) -> OpenrConfig:
    if args.config:
        return OpenrConfig.from_file(args.config)
    if not args.node_name:
        raise SystemExit("either --config or --node-name is required")
    from openr_tpu.config.config import AreaConfig, LinkMonitorConfig

    return OpenrConfig(
        node_name=args.node_name,
        areas=[AreaConfig(area_id=a) for a in args.areas.split(",")],
        openr_ctrl_port=args.ctrl_port,
        dryrun=args.dryrun,
        enable_v4=args.enable_v4,
        link_monitor=LinkMonitorConfig(use_rtt_metric=args.use_rtt_metric),
        solver_backend=args.solver_backend,
    )


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    config = build_config(args)
    log = logging.getLogger("openr_tpu.main")
    log.info("starting openr-tpu node %s", config.node_name)

    from openr_tpu.config_store.persistent_store import PersistentStore

    config_store = PersistentStore(config.persistent_store_path)
    io_provider = UdpIoProvider(port=args.spark_port)
    area = config.areas[0].area_id

    if args.fib_agent_port and args.enable_netlink_fib:
        raise SystemExit(
            "--fib-agent-port and --enable-netlink-fib are mutually "
            "exclusive: the agent owns the kernel boundary"
        )
    fib_agent = None  # MockFibAgent default
    if args.fib_agent_port:
        from openr_tpu.platform.netlink_fib_handler import TcpFibAgent

        fib_agent = TcpFibAgent("127.0.0.1", args.fib_agent_port)
        log.info("using platform agent on port %d", args.fib_agent_port)
    elif args.enable_netlink_fib:
        from openr_tpu.platform.netlink_fib_handler import NetlinkFibHandler
        from openr_tpu.platform.netlink_linux import (
            LinuxNetlinkProtocolSocket,
        )

        # an explicitly requested kernel FIB must not silently degrade
        # to the in-memory mock
        if not LinuxNetlinkProtocolSocket.is_admin_available():
            raise SystemExit(
                "--enable-netlink-fib requires rtnetlink access "
                "(CAP_NET_ADMIN); use --mock on the standalone agent "
                "for simulation"
            )
        fib_agent = NetlinkFibHandler(LinuxNetlinkProtocolSocket())
        log.info("in-process netlink FIB handler (rtnetlink)")

    node = OpenrNode(
        config.node_name,
        io_provider,
        fib_agent=fib_agent,
        area=area,
        spark_config=dict(
            hello_interval_s=config.spark.hello_time_s,
            fast_hello_interval_s=config.spark.fastinit_hello_time_ms / 1000,
            handshake_interval_s=config.spark.handshake_time_ms / 1000,
            heartbeat_interval_s=config.spark.keepalive_time_s,
            hold_time_s=config.spark.hold_time_s,
            graceful_restart_time_s=config.spark.graceful_restart_time_s,
        ),
        use_rtt_metric=config.link_monitor.use_rtt_metric,
        config_store=config_store,
        solver_backend=config.solver_backend,
        debounce_min_s=config.decision.debounce_min_ms / 1000,
        debounce_max_s=config.decision.debounce_max_ms / 1000,
        enable_flood_optimization=config.kvstore.enable_flood_optimization,
        is_flood_root=config.kvstore.is_flood_root,
    )
    node.ctrl_handler._config = config

    watchdog = None
    if config.enable_watchdog:
        watchdog = Watchdog(
            interval_s=config.watchdog.interval_s,
            thread_timeout_s=config.watchdog.thread_timeout_s,
            max_memory_bytes=config.watchdog.max_memory_mb * 1024 * 1024,
        )
        for name, evb in (
            ("kvstore", node.kvstore.evb),
            ("decision", node.decision.evb),
            ("fib", node.fib.evb),
            ("spark", node.spark.evb),
            ("linkmonitor", node.link_monitor.evb),
            ("prefixmgr", node.prefix_manager.evb),
        ):
            watchdog.add_evb(name, evb)

    node.start()
    if watchdog is not None:
        watchdog.start()
    port = node.start_ctrl_server(port=config.openr_ctrl_port)
    log.info("ctrl server listening on port %d", port)

    for if_name in [i for i in args.ifaces.split(",") if i]:
        node.add_interface(if_name)
        log.info("tracking interface %s", if_name)

    stop_event = threading.Event()

    def on_signal(signum, frame):
        log.info("signal %d: shutting down", signum)
        stop_event.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    stop_event.wait()

    if watchdog is not None:
        watchdog.stop()
    node.stop()
    config_store.stop()
    log.info("shutdown complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
