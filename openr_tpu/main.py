"""openr-tpu daemon entry point.

The analogue of the reference's ``openr/Main.cpp`` main(): parse config
(JSON file via --config, or legacy flags), assemble the module graph,
start the ctrl server and watchdog, run until SIGINT/SIGTERM, tear down
in reverse order.

Run:  python -m openr_tpu.main --config node.json
      python -m openr_tpu.main --node-name fc001 --ifaces eth0,eth1
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from openr_tpu.config.config import OpenrConfig
from openr_tpu.daemon import OpenrNode
from openr_tpu.monitor.watchdog import Watchdog
from openr_tpu.spark.io_provider import UdpIoProvider


def _is_legacy_invocation(argv) -> bool:
    """A reference-style gflags invocation is detected by any
    underscore-named flag from the translated gflag subset
    (``--node_name=...``). The native argparse surface uses dashes, so
    the two dialects never overlap on a single argument."""
    from openr_tpu.config.gflags import GFLAG_DEFS

    for arg in argv:
        if not arg.startswith("--"):
            continue
        name = arg[2:].partition("=")[0]
        if "_" not in name:
            continue
        if name in GFLAG_DEFS or (
            name.startswith("no") and name[2:] in GFLAG_DEFS
        ):
            return True
    return False


def parse_args(argv):
    parser = _build_parser()
    if _is_legacy_invocation(argv):
        # the WHOLE argv goes through the gflag shim: mixing it into
        # argparse would silently strip flags the two surfaces share
        # (--areas, --dryrun, --config). Parsing an empty argv gives the
        # native defaults, so both paths share one attribute contract.
        args = parser.parse_args([])
        args.legacy_argv = list(argv)
        return args
    # strict parse: unknown/typo'd flags must fail fast
    args = parser.parse_args(argv)
    args.legacy_argv = None
    return args


def _build_parser():
    parser = argparse.ArgumentParser(prog="openr-tpu")
    parser.add_argument("--config", help="JSON config file")
    # legacy flag surface (reference: 99 gflags in common/Flags.cpp;
    # the load-bearing subset)
    parser.add_argument("--node-name", default=None)
    parser.add_argument("--areas", default="0")
    parser.add_argument("--ifaces", default="", help="comma separated")
    parser.add_argument("--ctrl-port", type=int, default=2018)
    parser.add_argument("--dryrun", action="store_true")
    parser.add_argument("--enable-v4", action="store_true")
    parser.add_argument("--use-rtt-metric", action="store_true")
    parser.add_argument("--solver-backend", default="device",
                        choices=["device", "host"])
    parser.add_argument(
        "--enable-netlink-fib", action="store_true",
        help="program routes into the kernel via an in-process "
             "NetlinkFibHandler over rtnetlink (reference: "
             "Main.cpp:343-361)",
    )
    parser.add_argument(
        "--fib-agent-port", type=int, default=0,
        help="connect to an out-of-process platform agent "
             "(python -m openr_tpu.platform.agent) instead",
    )
    parser.add_argument(
        "--fib-agent-thrift", action="store_true",
        help="the platform agent speaks the reference FibService "
             "thrift wire (e.g. an FBOSS-style switch agent, or "
             "openr_tpu.platform.agent --thrift)",
    )
    parser.add_argument(
        "--spark-port", type=int, default=None,
        help="UDP multicast port (default: config spark.mcast_port)",
    )
    parser.add_argument(
        "--tls-cert", default=None,
        help="serve the ctrl API over TLS with this PEM cert chain "
             "(reference: the thrift ctrl server's optional TLS; the "
             "breeze client auto-falls-back secure -> plain)",
    )
    parser.add_argument(
        "--tls-key", default=None,
        help="PEM private key for --tls-cert",
    )
    parser.add_argument("-v", "--verbose", action="count", default=0)
    return parser


def build_config(args) -> OpenrConfig:
    if getattr(args, "legacy_argv", None) is not None:
        # reference-style gflags invocation (--node_name=... etc.):
        # translate through the shim (reference: config/GflagConfig.h)
        from openr_tpu.config.gflags import load_config_from_argv

        return load_config_from_argv(args.legacy_argv)
    if args.config:
        return OpenrConfig.from_file(args.config)
    if not args.node_name:
        raise SystemExit("either --config or --node-name is required")
    from openr_tpu.config.config import AreaConfig, LinkMonitorConfig

    return OpenrConfig(
        node_name=args.node_name,
        areas=[AreaConfig(area_id=a) for a in args.areas.split(",")],
        openr_ctrl_port=args.ctrl_port,
        dryrun=args.dryrun,
        enable_v4=args.enable_v4,
        link_monitor=LinkMonitorConfig(use_rtt_metric=args.use_rtt_metric),
        solver_backend=args.solver_backend,
    )


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    config = build_config(args)
    log = logging.getLogger("openr_tpu.main")
    log.info("starting openr-tpu node %s", config.node_name)

    # persistent XLA compilation cache: daemon restarts skip straight
    # past the remote-compile tunnel for every already-seen kernel
    from openr_tpu.utils.compile_cache import enable as _enable_cache

    _enable_cache()

    if config.enable_solver_mesh:
        # process-global: every KSP2 engine this daemon builds shards
        # its resident all-pairs state over the local device mesh
        import jax

        from openr_tpu.decision import ksp2_engine
        from openr_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(jax.devices())
        ksp2_engine.set_engine_mesh(mesh)
        log.info(
            "solver mesh enabled: %d device(s), KSP2 engine bound %d",
            mesh.devices.size, ksp2_engine.engine_max_nodes(),
        )

    from openr_tpu.config_store.persistent_store import PersistentStore

    config_store = PersistentStore(config.persistent_store_path)
    spark_port = args.spark_port or config.spark.mcast_port
    io_provider = UdpIoProvider(port=spark_port)
    area = config.areas[0].area_id

    fib_agent_port = args.fib_agent_port
    enable_netlink_fib = (
        args.enable_netlink_fib or config.enable_netlink_fib_handler
    )
    if fib_agent_port and enable_netlink_fib:
        raise SystemExit(
            "--fib-agent-port and --enable-netlink-fib are mutually "
            "exclusive: the agent owns the kernel boundary"
        )
    if args.fib_agent_thrift and not fib_agent_port:
        raise SystemExit(
            "--fib-agent-thrift requires --fib-agent-port (otherwise "
            "the no-op mock agent would silently swallow every route)"
        )
    # pure argument validation: a bad cert invocation must die BEFORE
    # the daemon starts announcing itself, not flap neighbors after
    ssl_context = None
    if bool(args.tls_cert) != bool(args.tls_key):
        raise SystemExit("--tls-cert and --tls-key go together")
    if args.tls_cert:
        import ssl

        ssl_context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        try:
            ssl_context.load_cert_chain(args.tls_cert, args.tls_key)
        except (OSError, ssl.SSLError) as exc:
            raise SystemExit(f"--tls-cert/--tls-key: {exc}")
    fib_agent = None  # MockFibAgent default
    if fib_agent_port:
        if args.fib_agent_thrift:
            from openr_tpu.platform.thrift_fib import ThriftFibAgent

            fib_agent = ThriftFibAgent("127.0.0.1", fib_agent_port)
        else:
            from openr_tpu.platform.netlink_fib_handler import TcpFibAgent

            fib_agent = TcpFibAgent("127.0.0.1", fib_agent_port)
        log.info(
            "using platform agent on port %d (%s wire)",
            fib_agent_port,
            "thrift-compact" if args.fib_agent_thrift
            else "framework-rpc",
        )
    elif enable_netlink_fib:
        from openr_tpu.platform.netlink_fib_handler import NetlinkFibHandler
        from openr_tpu.platform.netlink_linux import (
            LinuxNetlinkProtocolSocket,
        )

        # an explicitly requested kernel FIB must not silently degrade
        # to the in-memory mock
        if not LinuxNetlinkProtocolSocket.is_admin_available():
            raise SystemExit(
                "--enable-netlink-fib requires rtnetlink access "
                "(CAP_NET_ADMIN); use --mock on the standalone agent "
                "for simulation"
            )
        fib_agent = NetlinkFibHandler(LinuxNetlinkProtocolSocket())
        log.info("in-process netlink FIB handler (rtnetlink)")

    # loopback address programming for the prefix allocator needs its own
    # netlink socket (the FIB handler owns route programming only)
    alloc_netlink = None
    if config.prefix_alloc.enabled and config.prefix_alloc.set_loopback_addr:
        from openr_tpu.platform.netlink_linux import (
            LinuxNetlinkProtocolSocket as _NlSock,
        )

        if _NlSock.is_admin_available():
            alloc_netlink = _NlSock()
        else:
            log.warning(
                "set_loopback_address requested but rtnetlink is not "
                "available (needs CAP_NET_ADMIN): the elected prefix "
                "will be advertised but NOT programmed on %s",
                config.prefix_alloc.loopback_iface,
            )

    # resolve tracked interfaces (and their areas) up front
    ifaces = [i for i in args.ifaces.split(",") if i]
    if not ifaces and args.legacy_argv is not None:
        # reference semantics: interfaces come from the system, filtered
        # by the configured area regexes (iface_regex_include/exclude) —
        # without this a gflags-started daemon would track nothing and
        # never form an adjacency
        import socket as _socket

        ifaces = [
            name
            for _, name in _socket.if_nameindex()
            if name != "lo"
            and any(a.matches_interface(name) for a in config.areas)
        ]
    interface_areas = {}
    for if_name in ifaces:
        for a in config.areas:
            if a.matches_interface(if_name):
                interface_areas[if_name] = a.area_id
                break

    # cross-process KvStore peering: neighbors advertise their peer
    # port in Spark handshakes (Spark.thrift:97 kvStoreCmdPort) and we
    # dial their link-local transport address. Wire selected by
    # kvstore.enable_kvstore_thrift (framed CompactProtocol interop vs
    # the framework RPC codec).
    def peer_transport_factory(nbr):
        if nbr.kvstore_peer_port <= 0:
            return None
        host = None
        if nbr.transport_address_v6.addr:
            host = nbr.transport_address_v6.to_str()
            if host.startswith("fe80"):
                host = f"{host}%{nbr.local_if_name}"
        elif nbr.transport_address_v4.addr:
            host = nbr.transport_address_v4.to_str()
        if not host:
            return None
        if config.kvstore.enable_kvstore_thrift:
            from openr_tpu.kvstore.thrift_peer import ThriftPeerTransport

            return ThriftPeerTransport(host, nbr.kvstore_peer_port)
        from openr_tpu.kvstore.transport import TcpPeerTransport

        return TcpPeerTransport(host, nbr.kvstore_peer_port)

    node = OpenrNode(
        config.node_name,
        io_provider,
        fib_agent=fib_agent,
        peer_transport_factory=peer_transport_factory,
        area=area,
        areas=config.area_ids(),
        interface_areas=interface_areas or None,
        spark_config=dict(
            hello_interval_s=config.spark.hello_time_s,
            fast_hello_interval_s=config.spark.fastinit_hello_time_ms / 1000,
            handshake_interval_s=config.spark.handshake_time_ms / 1000,
            heartbeat_interval_s=config.spark.keepalive_time_s,
            hold_time_s=config.spark.hold_time_s,
            graceful_restart_time_s=config.spark.graceful_restart_time_s,
            wire_format=config.spark.wire_format,
            domain=config.domain,
        ),
        use_rtt_metric=config.link_monitor.use_rtt_metric,
        config_store=config_store,
        solver_backend=config.solver_backend,
        enable_rib_policy=config.enable_rib_policy,
        enable_v4=config.enable_v4,
        enable_lfa=config.enable_lfa,
        enable_ordered_fib=config.enable_ordered_fib_programming,
        enable_bgp_route_programming=(
            config.decision.enable_bgp_route_programming
        ),
        enable_best_route_selection=config.enable_best_route_selection,
        enable_segment_routing=config.enable_segment_routing,
        node_label=config.node_label,
        debounce_min_s=config.decision.debounce_min_ms / 1000,
        debounce_max_s=config.decision.debounce_max_ms / 1000,
        enable_flood_optimization=config.kvstore.enable_flood_optimization,
        is_flood_root=config.kvstore.is_flood_root,
        flood_rate=config.kvstore.flood_rate(),
        per_prefix_keys=config.per_prefix_keys,
        prefix_alloc=config.prefix_alloc,
        netlink=alloc_netlink,
    )
    node.ctrl_handler._config = config

    watchdog = None
    if config.enable_watchdog:
        watchdog = Watchdog(
            interval_s=config.watchdog.interval_s,
            thread_timeout_s=config.watchdog.thread_timeout_s,
            max_memory_bytes=config.watchdog.max_memory_mb * 1024 * 1024,
        )
        for name, evb in (
            ("kvstore", node.kvstore.evb),
            ("decision", node.decision.evb),
            ("fib", node.fib.evb),
            ("spark", node.spark.evb),
            ("linkmonitor", node.link_monitor.evb),
            ("prefixmgr", node.prefix_manager.evb),
            ("monitor", node.monitor.evb),
        ):
            watchdog.add_evb(name, evb)

    # reference: Main.cpp:595-601 invokes pluginStart when BGP peering
    # is enabled — here the plugin hook is generic (daemon starts any
    # registered plugin, handing it config.bgp_config), so the gate's
    # counterpart is surfacing a peering section nobody will speak
    if config.is_bgp_peering_enabled():
        from openr_tpu import plugin

        if not plugin.has_plugin():
            log.warning(
                "bgp_config present (%d peers) but no plugin is "
                "registered to speak BGP — peering will not come up",
                len(config.bgp_config.peers),
            )

    # KvStore peer server: what neighbors dial for full-sync and flood
    # (reference: the thrift KvStoreService / legacy zmq ROUTER on port
    # 60002, Constants.h:257). The SERVER always dual-stacks — both
    # wires on the one advertised port, sniffed per connection — so
    # mixed deployments mid-migration sync regardless of which wire
    # each neighbor dials (the reference's dual-transport pattern,
    # KvStore.cpp:2940-2973). enable_kvstore_thrift selects only the
    # wire THIS daemon dials outward. Bound before Spark starts so the
    # handshake advertises a live port.
    from openr_tpu.kvstore.dualstack import DualStackPeerServer

    peer_server = DualStackPeerServer(
        node.kvstore, host="::", port=config.kvstore.peer_port
    )
    peer_server.start()
    node.spark.set_kvstore_peer_port(peer_server.port)
    log.info(
        "kvstore peer server (dual-stack; dialing %s) on port %d",
        "thrift-compact" if config.kvstore.enable_kvstore_thrift
        else "framework-rpc",
        peer_server.port,
    )

    node.start()
    if watchdog is not None:
        watchdog.start()
    port = node.start_ctrl_server(
        port=config.openr_ctrl_port, ssl_context=ssl_context
    )
    log.info(
        "ctrl server listening on port %d%s",
        port,
        " (TLS)" if ssl_context is not None else "",
    )

    for if_name in ifaces:
        node.add_interface(if_name)
        log.info(
            "tracking interface %s (area %s)",
            if_name,
            interface_areas.get(if_name, area),
        )

    stop_event = threading.Event()

    def on_signal(signum, frame):
        log.info("signal %d: shutting down", signum)
        stop_event.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    stop_event.wait()

    if watchdog is not None:
        watchdog.stop()
    peer_server.stop()
    node.stop()
    config_store.stop()
    log.info("shutdown complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
