"""Seedable open-loop KvStore event generator.

Synthesizes a realistic publication stream against a synthetic topology
(``openr_tpu.models.topologies``): metric churn on existing adjacencies,
link flaps (adjacency withdrawn then restored), and prefix updates
(loopback advertisements toggled). The mix is seedable and the whole
schedule is deterministic given (topology, seed, mix) — the property the
shed-by-coalescing oracle-parity check rests on: the *surviving* event
list replayed unshedded must land on the same LSDB.

Ninth fault seam: ``load.generator``. Arming it makes generated events
drop before mutating generator state (a lossy publisher), so chaos
storms can run *under* sustained load while the parity oracle still
holds — dropped events mutate nothing and are excluded from replay.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Tuple

from openr_tpu.faults.injector import (
    FaultInjected,
    fault_point,
    register_fault_site,
)
from openr_tpu.models.topologies import Topology
from openr_tpu.types import (
    TTL_INFINITY,
    Adjacency,
    AdjacencyDatabase,
    BinaryAddress,
    IpPrefix,
    PrefixDatabase,
    PrefixEntry,
    Value,
)
from openr_tpu.utils import keys as keyutil
from openr_tpu.utils import wire

FAULT_LOAD_GENERATOR = register_fault_site("load.generator")

KIND_METRIC = "metric_churn"
KIND_FLAP = "link_flap"
KIND_PREFIX = "prefix_update"
KIND_DRAIN = "drain_flip"


@dataclass(frozen=True)
class EventMix:
    """Relative weights of the event kinds (normalized at use).

    ``drain_flip`` (overload toggle — the twin's drain-sequencing
    scenario) defaults to 0.0 so every pre-existing (topology, seed,
    mix) schedule stays byte-identical: the kind pick still consumes
    exactly one RNG draw and the cumulative thresholds are unchanged
    when the new weight is zero."""

    metric_churn: float = 0.70
    link_flap: float = 0.15
    prefix_update: float = 0.15
    drain_flip: float = 0.0

    def cumulative(self) -> Tuple[float, float, float]:
        total = (self.metric_churn + self.link_flap
                 + self.prefix_update + self.drain_flip)
        assert total > 0
        c1 = self.metric_churn / total
        c2 = c1 + self.link_flap / total
        return (c1, c2, c2 + self.prefix_update / total)


@dataclass
class LoadEvent:
    """One generated publication (or a fault-dropped slot)."""

    seq: int
    kind: str
    node: str
    key: str = ""
    payload: Optional[bytes] = None
    version: int = 0
    dropped: bool = False


def _extra_prefix(node_idx: int) -> IpPrefix:
    # distinct from topologies._loopback_prefix's fd00::/16 range
    val = (0xFD10 << 112) | node_idx
    return IpPrefix(BinaryAddress(addr=val.to_bytes(16, "big")), 128)


class LoadGenerator:
    """Deterministic event stream over a mutable copy of ``topo``.

    The generator owns per-key version counters (continuing from the
    version-1 bulk initial load it also emits) and the evolving
    adjacency/prefix databases; ``next_event`` mutates that state and
    returns the key + serialized payload to publish.
    """

    def __init__(
        self,
        topo: Topology,
        seed: int = 0,
        mix: Optional[EventMix] = None,
    ):
        self._rng = random.Random(seed)
        self._mix = mix or EventMix()
        self.adj_dbs = dict(topo.adj_dbs)
        self.prefix_dbs = dict(topo.prefix_dbs)
        self._node_idx = {n: i for i, n in enumerate(sorted(self.adj_dbs))}
        self.versions: Dict[str, int] = {}
        # link flaps: (node, withdrawn Adjacency) awaiting restore
        self._down: List[Tuple[str, Adjacency]] = []
        # nodes currently advertising the extra prefix
        self._extra: Dict[str, bool] = {}
        self._seq = 0
        self.dropped = 0

    # -- initial load -----------------------------------------------------

    def initial_key_vals(self) -> Dict[str, Value]:
        """Version-1 Values for the whole topology, for one bulk
        ``set_key_vals`` (one debounce window, one cold build)."""
        out: Dict[str, Value] = {}
        for name in sorted(self.adj_dbs):
            key = keyutil.adj_key(name)
            payload = wire.dumps(self.adj_dbs[name])
            self.versions[key] = 1
            out[key] = Value(
                version=1,
                originator_id=name,
                value=payload,
                ttl=TTL_INFINITY,
                hash=wire.generate_hash(1, name, payload),
            )
        for name in sorted(self.prefix_dbs):
            key = keyutil.prefix_db_key(name)
            payload = wire.dumps(self.prefix_dbs[name])
            self.versions[key] = 1
            out[key] = Value(
                version=1,
                originator_id=name,
                value=payload,
                ttl=TTL_INFINITY,
                hash=wire.generate_hash(1, name, payload),
            )
        return out

    # -- event stream -----------------------------------------------------

    def next_event(self) -> LoadEvent:
        seq = self._seq
        self._seq += 1
        c1, c2, c3 = self._mix.cumulative()
        r = self._rng.random()
        kind = (
            KIND_METRIC if r < c1
            else KIND_FLAP if r < c2
            else KIND_PREFIX if r < c3
            else KIND_DRAIN
        )
        # the seam fires BEFORE any state mutation: a dropped event is a
        # pure no-op for the oracle (lossy publisher, not torn state)
        try:
            fault_point(FAULT_LOAD_GENERATOR)
        except FaultInjected:
            self.dropped += 1
            return LoadEvent(seq=seq, kind=kind, node="", dropped=True)
        if kind == KIND_METRIC:
            return self._metric_churn(seq)
        if kind == KIND_FLAP:
            return self._link_flap(seq)
        if kind == KIND_DRAIN:
            return self._drain_flip(seq)
        return self._prefix_update(seq)

    def events(self, n: int) -> List[LoadEvent]:
        return [self.next_event() for _ in range(n)]

    # -- scripted seams (the twin's scenario driver) ----------------------

    def emit_adjacency(
        self,
        node: str,
        db: Optional[AdjacencyDatabase] = None,
        kind: str = "scripted",
    ) -> LoadEvent:
        """Scripted-event seam: replace ``node``'s adjacency database
        (when given) and emit the publication event. Consumes NO RNG
        draws, so scripted steps interleave with the seeded stream
        without perturbing its schedule."""
        if db is not None:
            self.adj_dbs[node] = db
        seq = self._seq
        self._seq += 1
        return self._emit_adj(seq, kind, node)

    def emit_prefix(
        self,
        node: str,
        db: Optional[PrefixDatabase] = None,
        kind: str = KIND_PREFIX,
    ) -> LoadEvent:
        """Scripted prefix-advertisement seam (same no-RNG contract as
        ``emit_adjacency``)."""
        if db is not None:
            self.prefix_dbs[node] = db
        seq = self._seq
        self._seq += 1
        key = keyutil.prefix_db_key(node)
        v = self.versions[key] = self.versions.get(key, 0) + 1
        return LoadEvent(
            seq=seq,
            kind=kind,
            node=node,
            key=key,
            payload=wire.dumps(self.prefix_dbs[node]),
            version=v,
        )

    # -- kinds ------------------------------------------------------------

    def _emit_adj(self, seq: int, kind: str, node: str) -> LoadEvent:
        key = keyutil.adj_key(node)
        v = self.versions[key] = self.versions.get(key, 0) + 1
        return LoadEvent(
            seq=seq,
            kind=kind,
            node=node,
            key=key,
            payload=wire.dumps(self.adj_dbs[node]),
            version=v,
        )

    def _metric_churn(self, seq: int) -> LoadEvent:
        nodes = sorted(n for n, db in self.adj_dbs.items() if db.adjacencies)
        node = nodes[int(self._rng.random() * len(nodes)) % len(nodes)]
        db = self.adj_dbs[node]
        adjs = list(db.adjacencies)
        i = int(self._rng.random() * len(adjs)) % len(adjs)
        adjs[i] = replace(adjs[i], metric=1 + (adjs[i].metric % 10))
        self.adj_dbs[node] = replace(db, adjacencies=tuple(adjs))
        return self._emit_adj(seq, KIND_METRIC, node)

    def _link_flap(self, seq: int) -> LoadEvent:
        restore = bool(self._down) and self._rng.random() < 0.5
        if restore:
            node, adj = self._down.pop(0)
            db = self.adj_dbs[node]
            self.adj_dbs[node] = replace(
                db, adjacencies=db.adjacencies + (adj,)
            )
            return self._emit_adj(seq, KIND_FLAP, node)
        # withdraw one adjacency from a node that keeps >= 2 (never
        # isolate a node: an unreachable originator changes best-route
        # semantics, which would make parity depend on timing)
        nodes = sorted(
            n for n, db in self.adj_dbs.items() if len(db.adjacencies) >= 2
        )
        if not nodes:
            return self._metric_churn(seq)
        node = nodes[int(self._rng.random() * len(nodes)) % len(nodes)]
        db = self.adj_dbs[node]
        adjs = list(db.adjacencies)
        i = int(self._rng.random() * len(adjs)) % len(adjs)
        adj = adjs.pop(i)
        self.adj_dbs[node] = replace(db, adjacencies=tuple(adjs))
        self._down.append((node, adj))
        return self._emit_adj(seq, KIND_FLAP, node)

    def _drain_flip(self, seq: int) -> LoadEvent:
        """Drain/undrain: toggle ``is_overloaded`` on one node's
        adjacency database. An undrain is preferred when any node is
        drained and the coin lands that way (mirror of the flap
        restore discipline), and a node is never drained if that would
        leave zero undrained nodes — an all-overloaded fabric has no
        transit path at all, which would make parity timing-dependent
        the same way an isolated originator would."""
        drained = sorted(
            n for n, db in self.adj_dbs.items() if db.is_overloaded
        )
        undrain = bool(drained) and self._rng.random() < 0.5
        if undrain:
            node = drained[
                int(self._rng.random() * len(drained)) % len(drained)
            ]
            self.adj_dbs[node] = replace(
                self.adj_dbs[node], is_overloaded=False
            )
            return self._emit_adj(seq, KIND_DRAIN, node)
        candidates = sorted(
            n for n, db in self.adj_dbs.items() if not db.is_overloaded
        )
        if len(candidates) <= 1:
            return self._metric_churn(seq)
        node = candidates[
            int(self._rng.random() * len(candidates)) % len(candidates)
        ]
        self.adj_dbs[node] = replace(
            self.adj_dbs[node], is_overloaded=True
        )
        return self._emit_adj(seq, KIND_DRAIN, node)

    def _prefix_update(self, seq: int) -> LoadEvent:
        nodes = sorted(self.prefix_dbs)
        node = nodes[int(self._rng.random() * len(nodes)) % len(nodes)]
        db = self.prefix_dbs[node]
        extra = _extra_prefix(self._node_idx[node])
        if self._extra.get(node):
            del self._extra[node]
            entries = tuple(
                e for e in db.prefix_entries if e.prefix != extra
            )
        else:
            self._extra[node] = True
            base = db.prefix_entries[0] if db.prefix_entries else None
            entry = (
                replace(base, prefix=extra)
                if base is not None
                else PrefixEntry(prefix=extra)
            )
            entries = db.prefix_entries + (entry,)
        self.prefix_dbs[node] = replace(db, prefix_entries=entries)
        key = keyutil.prefix_db_key(node)
        v = self.versions[key] = self.versions.get(key, 0) + 1
        return LoadEvent(
            seq=seq,
            kind=KIND_PREFIX,
            node=node,
            key=key,
            payload=wire.dumps(self.prefix_dbs[node]),
            version=v,
        )
