"""Sustained-load harness: drive the real pipeline at a target rate.

Assembles the actual KvStore → Decision → Fib module pipeline (same
wiring as the daemon: ReplicateQueues between per-module event bases),
pumps a seeded ``LoadGenerator`` stream at a target events/s, and
measures:

- p50/p95/p99 end-to-end convergence, sampled per retired trace through
  the tracer's finish-listener (the 256-deep export ring overflows in
  ~1 s at these rates);
- queue backpressure: reader depth high-watermark during the window,
  drain time after it, overflow/shed/coalesce counters;
- WARM/cold solve mix from the telemetry registry.

Two modes: ``run_fixed_rate`` (one sustained window + drain + verdict)
and ``find_max_sustainable_rate`` (binary search for the highest rate
whose p99 meets the SLO and whose backlog drains).

Oracle parity: every published event is journaled; ``check_parity``
replays the journal — unshedded, single-threaded — through a fresh
Decision and compares canonical RouteDatabases bit-for-bit, proving
shed-by-coalescing and pipelined emit never changed net effect.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from openr_tpu.load.admission import AdmissionConfig, AdmissionControl
from openr_tpu.load.generator import EventMix, LoadGenerator
from openr_tpu.models import topologies
from openr_tpu.telemetry import get_registry, get_tracer
from openr_tpu.types import DEFAULT_AREA, KeySetParams, Publication, Value
from openr_tpu.utils import wire

# registry counters reported per window (as deltas across the window)
_WINDOW_COUNTERS = (
    "decision.admission.sheds",
    "decision.admission.shed_keys",
    "decision.admission.pubs_coalesced",
    "decision.admission.prewarm_skipped",
    "decision.coalesced_publications",
    "decision.debounce_widenings",
    "decision.debounce_narrowings",
    "decision.debounce_spans_reclaimed",
    "decision.ell_patches",
    "decision.ell_full_compiles",
    "decision.device_state_resets",
    "telemetry.traces_merged",
    "telemetry.traces_unclosed_spans",
    "telemetry.traces_bad_nesting",
    "faults.injected.load.generator",
)


def percentiles(samples: List[float]) -> Dict[str, Optional[float]]:
    """p50/p95/p99 with linear interpolation (same convention as the
    benchmark suite's _latency_percentiles)."""
    out: Dict[str, Optional[float]] = {"p50": None, "p95": None, "p99": None}
    if not samples:
        return out
    s = sorted(samples)

    def rank(q: float) -> float:
        if len(s) == 1:
            return s[0]
        pos = q * (len(s) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(s) - 1)
        return s[lo] + (s[hi] - s[lo]) * (pos - lo)

    out["p50"] = round(rank(0.50), 3)
    out["p95"] = round(rank(0.95), 3)
    out["p99"] = round(rank(0.99), 3)
    return out


@dataclass
class RateReport:
    """One fixed-rate window's outcome."""

    rate: int
    duration_s: float
    offered: int = 0  # generator events drawn (incl. fault-dropped)
    published: int = 0
    gen_dropped: int = 0  # load.generator seam drops
    achieved_rate: float = 0.0
    e2e_ms: Dict[str, Optional[float]] = field(default_factory=dict)
    e2e_samples: int = 0
    traces_malformed: int = 0
    depth_hwm: int = 0
    drain_s: Optional[float] = None
    drained: bool = False
    counters: Dict[str, float] = field(default_factory=dict)
    sustainable: Optional[bool] = None

    def to_dict(self) -> dict:
        return {
            "rate": self.rate,
            "duration_s": self.duration_s,
            "offered": self.offered,
            "published": self.published,
            "gen_dropped": self.gen_dropped,
            "achieved_rate": round(self.achieved_rate, 1),
            "e2e_ms": self.e2e_ms,
            "e2e_samples": self.e2e_samples,
            "traces_malformed": self.traces_malformed,
            "depth_hwm": self.depth_hwm,
            "drain_s": (
                round(self.drain_s, 3) if self.drain_s is not None else None
            ),
            "drained": self.drained,
            "counters": self.counters,
            "sustainable": self.sustainable,
        }


class SustainedLoadHarness:
    """Owns the pipeline + generator + journal for one load session."""

    def __init__(
        self,
        nodes: int = 64,
        seed: int = 20260805,
        mix: Optional[EventMix] = None,
        solver_backend: str = "host",
        debounce_min_s: float = 0.010,
        debounce_max_s: float = 0.100,
        admission: Optional[AdmissionConfig] = None,
        pipelined_emit: bool = True,
        area: str = DEFAULT_AREA,
    ):
        # real-module imports live here so importing openr_tpu.load (as
        # decision does, for the admission half) never pulls in Decision
        from openr_tpu.decision.decision import Decision
        from openr_tpu.fib.fib import Fib
        from openr_tpu.kvstore.wrapper import KvStoreWrapper
        from openr_tpu.messaging.queue import ReplicateQueue
        from openr_tpu.platform.fib_service import MockFibAgent

        self.area = area
        self.topo = topologies.fat_tree_nodes(nodes)
        self.generator = LoadGenerator(self.topo, seed=seed, mix=mix)
        self.my_node = next(
            k for k in sorted(self.topo.adj_dbs) if k.startswith("rsw")
        )
        self.store = KvStoreWrapper(f"load:{self.my_node}", areas=[area])
        self.route_q = ReplicateQueue(name="routeUpdates")
        self.decision = Decision(
            self.my_node,
            kvstore_updates_queue=self.store.store.updates_queue,
            route_updates_queue=self.route_q,
            debounce_min_s=debounce_min_s,
            debounce_max_s=debounce_max_s,
            solver_backend=solver_backend,
            admission=AdmissionControl(admission or AdmissionConfig()),
            pipelined_emit=pipelined_emit,
        )
        self.fib = Fib(
            self.my_node,
            MockFibAgent(),
            self.route_q,
            keepalive_interval_s=30.0,
            area=area,
        )
        self._solver_backend = solver_backend
        # parity journal: (key, Value) in publish order, plus the bulk
        # initial load — everything the oracle replays
        self._initial: Dict[str, Value] = {}
        self._journal: List[Tuple[str, Value]] = []
        self._started = False

    # -- lifecycle --------------------------------------------------------

    def start(self, initial_timeout_s: float = 600.0) -> None:
        self.store.start()
        self.decision.start()
        self.fib.start()
        self._initial = self.generator.initial_key_vals()
        self.store.store.set_key_vals(
            self.area, KeySetParams(key_vals=dict(self._initial))
        )
        assert self._wait_until(
            lambda: len(self.fib.get_route_db().unicast_routes) > 0,
            initial_timeout_s,
        ), "initial convergence timed out"
        self.drain()
        self._started = True

    def stop(self) -> None:
        self.fib.stop()
        self.decision.stop()
        self.store.stop()
        self._started = False

    def __enter__(self) -> "SustainedLoadHarness":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- load -------------------------------------------------------------

    def run_fixed_rate(
        self,
        rate: int,
        duration_s: float,
        drain_grace_s: float = 20.0,
        p99_slo_ms: Optional[float] = None,
    ) -> RateReport:
        """One open-loop window at ``rate`` events/s, then a bounded
        drain. The publisher never blocks on the pipeline (that's the
        point); backpressure shows up as reader depth, widened
        debounce, and shed counters instead."""
        assert self._started, "call start() first"
        report = RateReport(rate=rate, duration_s=duration_s)
        samples: List[float] = []
        malformed = [0]
        lock = threading.Lock()

        def on_finish(trace, ok: bool) -> None:
            with lock:
                if not (ok and trace.well_formed()):
                    malformed[0] += 1
                elif trace.e2e_ms is not None:
                    samples.append(trace.e2e_ms)

        tracer = get_tracer()
        reg = get_registry()
        c0 = {k: reg.counter_get(k) for k in _WINDOW_COUNTERS}
        tracer.add_finish_listener(on_finish)
        reader = self.decision._kv_reader
        interval = 1.0 / max(1, rate)
        t0 = time.monotonic()
        deadline = t0
        try:
            while True:
                now = time.monotonic()
                if now - t0 >= duration_s:
                    break
                ev = self.generator.next_event()
                report.offered += 1
                if ev.dropped:
                    report.gen_dropped += 1
                else:
                    self.store.set_key(
                        ev.key,
                        ev.payload,
                        version=ev.version,
                        area=self.area,
                        originator=ev.node,
                    )
                    self._journal.append(
                        (
                            ev.key,
                            Value(
                                version=ev.version,
                                originator_id=ev.node,
                                value=ev.payload,
                                ttl=self._initial[ev.key].ttl,
                                hash=wire.generate_hash(
                                    ev.version, ev.node, ev.payload
                                ),
                            ),
                        )
                    )
                    report.published += 1
                report.depth_hwm = max(report.depth_hwm, reader.size())
                deadline += interval
                sleep = deadline - time.monotonic()
                if sleep > 0:
                    time.sleep(sleep)
            elapsed = time.monotonic() - t0
            report.achieved_rate = (
                report.offered / elapsed if elapsed > 0 else 0.0
            )
            t_drain0 = time.monotonic()
            report.drained = self.drain(timeout_s=drain_grace_s)
            report.drain_s = time.monotonic() - t_drain0
        finally:
            tracer.remove_finish_listener(on_finish)
        with lock:
            report.e2e_ms = percentiles(samples)
            report.e2e_samples = len(samples)
            report.traces_malformed = malformed[0]
        report.counters = {
            k: reg.counter_get(k) - c0[k]
            for k in _WINDOW_COUNTERS
            if reg.counter_get(k) - c0[k]
        }
        if p99_slo_ms is not None:
            p99 = report.e2e_ms.get("p99")
            report.sustainable = bool(
                report.drained
                and report.e2e_samples > 0
                and p99 is not None
                and p99 <= p99_slo_ms
            )
        return report

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Wait for the pipeline to go quiescent: empty Decision reader,
        no pending debounce, emit stage flushed, Fib caught up."""
        reader = self.decision._kv_reader
        debounce = self.decision._rebuild_debounced
        ok = self._wait_until(
            lambda: reader.size() == 0 and not debounce.is_scheduled(),
            timeout_s,
        )
        # flush the pipelined emit stage and any queued evb callbacks
        self.decision.evb.call_and_wait(self.decision._drain_emit)
        # Fib: its reader must drain too (route programming is the last
        # trace stage)
        reg = get_registry()
        stable_since = time.monotonic()
        last = reg.counter_get("telemetry.traces_finished")
        deadline = time.monotonic() + max(2.0, timeout_s / 4)
        while time.monotonic() < deadline:
            time.sleep(0.05)
            cur = reg.counter_get("telemetry.traces_finished")
            if cur != last:
                last = cur
                stable_since = time.monotonic()
            elif time.monotonic() - stable_since > 0.4:
                break
        return ok

    @staticmethod
    def _wait_until(pred, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.005)
        return bool(pred())

    # -- oracle parity ----------------------------------------------------

    def oracle_route_db(self):
        """Replay the journal — full, unshedded, single-threaded — into
        a fresh Decision on the deterministic host backend and return
        its final DecisionRouteDb."""
        from openr_tpu.decision.decision import Decision
        from openr_tpu.messaging.queue import ReplicateQueue

        kv_q = ReplicateQueue(name="oracle:kvstore")
        oracle = Decision(
            self.my_node,
            kvstore_updates_queue=kv_q,
            route_updates_queue=ReplicateQueue(name="oracle:routes"),
            solver_backend="host",
        )
        try:
            oracle.process_publication(
                Publication(key_vals=dict(self._initial), area=self.area)
            )
            for key, value in self._journal:
                oracle.process_publication(
                    Publication(key_vals={key: value}, area=self.area)
                )
            oracle.pending.set_needs_full_rebuild()
            oracle.rebuild_routes("ORACLE")
            return oracle.route_db
        finally:
            kv_q.close()  # releases the oracle's reader forwarder thread

    def live_route_db(self):
        """The pipeline Decision's installed DecisionRouteDb (call after
        ``drain()``)."""
        self.decision.evb.call_and_wait(self.decision._drain_emit)
        return self.decision.route_db

    def check_parity(self) -> bool:
        """Shed-by-coalescing + pipelined emit vs the unshedded oracle:
        the canonical RouteDatabase must match bit for bit. The live
        solve may have run on a different backend than the host oracle —
        cross-backend parity is the parity suite's own guarantee."""
        live = wire.dumps(self.live_route_db().to_route_db(self.my_node))
        want = wire.dumps(self.oracle_route_db().to_route_db(self.my_node))
        return live == want

    # -- closed-loop controller ------------------------------------------

    def find_max_sustainable_rate(
        self,
        p99_slo_ms: float,
        lo: int = 25,
        hi: int = 800,
        duration_s: float = 2.0,
        max_probes: int = 6,
    ) -> dict:
        """Binary-search the highest events/s whose p99 meets the SLO
        and whose backlog drains. ``lo`` is assumed (and verified)
        sustainable; ``hi`` is the search ceiling."""
        ladder: List[RateReport] = []
        floor = self.run_fixed_rate(
            lo, duration_s, p99_slo_ms=p99_slo_ms
        )
        ladder.append(floor)
        best = lo if floor.sustainable else 0
        if floor.sustainable:
            probes = 0
            lo_r, hi_r = lo, hi
            while probes < max_probes and hi_r - lo_r > max(1, lo // 4):
                mid = (lo_r + hi_r) // 2
                rep = self.run_fixed_rate(
                    mid, duration_s, p99_slo_ms=p99_slo_ms
                )
                ladder.append(rep)
                probes += 1
                if rep.sustainable:
                    best = max(best, mid)
                    lo_r = mid
                else:
                    hi_r = mid
        return {
            "slo_p99_ms": p99_slo_ms,
            "max_sustainable_rate": best,
            "probes": len(ladder),
            "ladder": [r.to_dict() for r in ladder],
        }
