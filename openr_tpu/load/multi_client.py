"""Multi-process client driver for the solver service.

The PR 8 load harness drives one in-process pipeline; this module
drives a ``SolverService`` the way production would be driven — N
OS-process client daemons, each owning a disjoint set of tenants,
registering worlds, churning metrics, and soliciting views over the
ctrl wire. Child processes are JAX-FREE (serve/client.py + the
topology generators only), so spawn startup is milliseconds and the
one device owner stays the service process.

Everything is deterministic from the spec: the world a tenant
registers and the metric it churns on round ``i`` derive only from
``(spec, i)``, so a parent (test or smoke gate) replays the same
schedule host-side to produce oracle digests without any channel back
from the children beyond the result files.

``run_client`` is module-level and takes only picklable arguments —
required by the ``spawn`` start method (the only safe method with a
jax parent).
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, replace
from typing import Dict, List, Tuple


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's deterministic world + churn schedule."""

    tenant_id: str
    kind: str          # "grid" | "ring" | "mesh"
    size: int
    seed: int = 0
    slo: str = "standard"

    def build_topology(self):
        from openr_tpu.models import topologies

        if self.kind == "grid":
            return topologies.grid(self.size)
        if self.kind == "ring":
            return topologies.ring(self.size)
        if self.kind == "mesh":
            return topologies.random_mesh(
                self.size, 3, seed=self.seed or 7
            )
        raise ValueError(f"unknown topology kind {self.kind!r}")

    def build_dbs(self) -> Dict[str, "object"]:
        return dict(self.build_topology().adj_dbs)

    def build_prefix_dbs(self) -> Dict[str, "object"]:
        """Per-node loopback PrefixDatabases — what the FIB-level view
        routes toward (static across the churn schedule: mutations
        touch adjacency metrics only)."""
        return dict(self.build_topology().prefix_dbs)

    def root_of(self, dbs: Dict) -> str:
        return sorted(dbs)[0]

    def mutation(self, dbs: Dict, round_i: int) -> Tuple[str, object]:
        """The round's churn: ONE adjacency metric bump on a
        deterministically chosen node. Returns (node, new_db); pure —
        parent oracles replay it bit-identically."""
        names = sorted(dbs)
        node = names[(round_i * 3 + self.seed) % len(names)]
        db = dbs[node]
        adjs = list(db.adjacencies)
        if not adjs:
            node = names[0]
            db = dbs[node]
            adjs = list(db.adjacencies)
        ai = (round_i + self.seed) % len(adjs)
        metric = 1 + ((round_i * 7 + self.seed * 5 + ai) % 13)
        adjs[ai] = replace(adjs[ai], metric=metric)
        return node, replace(db, adjacencies=tuple(adjs))


def apply_mutation(dbs: Dict, spec: TenantSpec, round_i: int) -> str:
    """Mutate ``dbs`` in place per the schedule; returns the node."""
    node, db = spec.mutation(dbs, round_i)
    dbs[node] = db
    return node


def run_client(
    host: str,
    port: int,
    client_id: str,
    specs: List[Dict],
    rounds: int,
    out_path: str,
    ksp2_every: int = 0,
    hold_open_s: float = 0.0,
    endpoints: Dict[str, List] = None,
    controller: List = None,
    fib_every: int = 0,
) -> None:
    """Child-process entry: drive ``specs``' tenants for ``rounds``
    churn rounds and write a JSON result file — per-request latencies
    (by SLO class), the per-tenant view digest after every round, and
    any errors. ``ksp2_every > 0`` also solicits the second-path view
    every that-many rounds (digested as the JSON text of the reply).
    ``hold_open_s`` keeps the connection (and its tenants) alive after
    the last round — the disconnect tests use it.

    Fleet mode: ``endpoints`` maps tenant_id -> [host, port] (the
    controller's admission decisions; tenants without an entry use the
    default endpoint), ``controller`` is the fleet controller's
    [host, port] for lookup fallback after an endpoint dies, and
    ``fib_every > 0`` also consumes the FIB-level view (full
    RouteDatabase digest) every that-many rounds. The client rides
    migrations and promotions transparently — redirect/reconnect
    totals land in the result for the parity gates."""
    from openr_tpu.serve.client import SolverClient

    result = {
        "client_id": client_id,
        "latencies_ms": {},
        "digests": {},
        "ksp2": {},
        "fib": {},
        "errors": [],
        "rounds": 0,
        "trace_id": None,
        "span_ids": [],
        "redirects": 0,
        "reconnects": 0,
    }
    clients: Dict[tuple, SolverClient] = {}
    ctrl_ep = tuple(controller) if controller else None

    def client_for(tid: str) -> SolverClient:
        ep = (host, port)
        if endpoints and tid in endpoints:
            e = endpoints[tid]
            ep = (str(e[0]), int(e[1]))
        c = clients.get(ep)
        if c is None:
            c = clients[ep] = SolverClient(
                ep[0], ep[1], controller=ctrl_ep
            )
        return c

    try:
        worlds = {}
        for sd in specs:
            spec = TenantSpec(**sd)
            dbs = spec.build_dbs()
            worlds[spec.tenant_id] = (spec, dbs)
            client = client_for(spec.tenant_id)
            # reported back so the parent gate can check cross-wire
            # trace continuity: these ids must surface in the
            # SERVICE's wave flight records
            if result["trace_id"] is None:
                result["trace_id"] = client.trace_id
            client.register(spec.tenant_id, slo=spec.slo)
            client.update_world(
                spec.tenant_id, [dbs[k] for k in sorted(dbs)],
                root=spec.root_of(dbs),
                prefix_dbs=(
                    [
                        db for _k, db in sorted(
                            spec.build_prefix_dbs().items()
                        )
                    ]
                    if fib_every else None
                ),
            )
            result["digests"][spec.tenant_id] = []
            result["ksp2"][spec.tenant_id] = []
            result["fib"][spec.tenant_id] = []
        for i in range(rounds):
            for tid, (spec, dbs) in worlds.items():
                client = client_for(tid)
                if i > 0:
                    node = apply_mutation(dbs, spec, i)
                    client.update_world(tid, [dbs[node]])
                t0 = time.perf_counter()
                view = client.solve(tid)
                ms = (time.perf_counter() - t0) * 1000.0
                result["latencies_ms"].setdefault(
                    spec.slo, []
                ).append(ms)
                result["digests"][tid].append(view.digest())
                if ksp2_every and (i + 1) % ksp2_every == 0:
                    paths = client.ksp2(
                        tid, sorted(view.nodes[:8])
                    )
                    result["ksp2"][tid].append(
                        _digest_text(json.dumps(paths, sort_keys=True))
                    )
                if fib_every and (i + 1) % fib_every == 0:
                    result["fib"][tid].append(
                        client.fib(tid).digest
                    )
            result["rounds"] = i + 1
        for c in clients.values():
            result["span_ids"].extend(list(c.span_ids))
            result["redirects"] += c.redirects
            result["reconnects"] += c.reconnects
        if hold_open_s > 0:
            time.sleep(hold_open_s)
        for c in clients.values():
            c.close()
    except Exception as exc:  # noqa: BLE001 - reported in the artifact
        result["errors"].append(repr(exc))
    with open(out_path, "w") as f:
        json.dump(result, f)


def _digest_text(text: str) -> int:
    h = 0x811C9DC5
    for b in text.encode("utf-8"):
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


def spawn_clients(
    host: str,
    port: int,
    client_specs: Dict[str, List[TenantSpec]],
    rounds: int,
    out_dir: str,
    ksp2_every: int = 0,
    hold_open_s: float = 0.0,
    endpoints: Dict[str, List] = None,
    controller: List = None,
    fib_every: int = 0,
):
    """Launch one spawn-context process per client; returns
    ``[(proc, out_path)]`` for the parent to join and harvest.
    ``endpoints``/``controller``/``fib_every`` pass through to
    ``run_client`` for the fleet mode."""
    import multiprocessing as mp
    import os

    ctx = mp.get_context("spawn")
    procs = []
    for client_id, specs in client_specs.items():
        out_path = os.path.join(
            out_dir, f"solver_client_{client_id}.json"
        )
        p = ctx.Process(
            target=run_client,
            args=(
                host, port, client_id,
                [asdict(s) for s in specs], rounds, out_path,
            ),
            kwargs=dict(
                ksp2_every=ksp2_every, hold_open_s=hold_open_s,
                endpoints=endpoints, controller=controller,
                fib_every=fib_every,
            ),
            daemon=True,
        )
        p.start()
        procs.append((p, out_path))
    return procs


def oracle_digests(
    specs: List[TenantSpec], rounds: int
) -> Dict[str, List[int]]:
    """Sequential single-graph oracle for the exact schedule
    ``run_client`` drives: per tenant, per round, the FNV digest of
    ``ell_view_batch_packed`` over the replayed world. Imports jax —
    parent/gate side only."""
    import numpy as np

    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.ops.spf_sparse import (
        compile_ell,
        ell_source_batch,
        ell_view_batch_packed,
    )

    out: Dict[str, List[int]] = {}
    for spec in specs:
        dbs = spec.build_dbs()
        ls = LinkState(area="0")
        for name in sorted(dbs):
            ls.update_adjacency_database(dbs[name])
        root = spec.root_of(dbs)
        digests = []
        for i in range(rounds):
            if i > 0:
                node = apply_mutation(dbs, spec, i)
                ls.update_adjacency_database(dbs[node])
            graph = compile_ell(ls)
            srcs = ell_source_batch(graph, ls, root)
            packed = np.asarray(
                ell_view_batch_packed(graph, srcs)
            ).astype(np.int32)
            h = 0x811C9DC5
            for b in packed.tobytes():
                h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
            digests.append(h)
        out[spec.tenant_id] = digests
    return out


def oracle_fib_digests(
    specs: List[TenantSpec], rounds: int, every: int
) -> Dict[str, List[int]]:
    """Never-migrated FIB oracle: replay each tenant's schedule on a
    local ``SpfSolver`` through the SAME recipe the ctrl handler uses
    (``fleet_preload_views`` over the packed ELL view, then
    ``build_route_db`` -> canonical ``RouteDatabase``), digesting the
    wire form on the rounds ``run_client(fib_every=every)`` samples.
    Imports jax — parent/gate side only."""
    import numpy as np

    from openr_tpu.decision.prefix_state import PrefixState
    from openr_tpu.decision.spf_solver import (
        SpfSolver,
        fleet_preload_views,
    )
    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.ops.spf_sparse import (
        compile_ell,
        ell_source_batch,
        ell_view_batch_packed,
    )
    from openr_tpu.utils import wire

    out: Dict[str, List[int]] = {}
    for spec in specs:
        dbs = spec.build_dbs()
        ls = LinkState(area="0")
        for name in sorted(dbs):
            ls.update_adjacency_database(dbs[name])
        root = spec.root_of(dbs)
        pfx = PrefixState()
        for _name, pdb in sorted(spec.build_prefix_dbs().items()):
            pfx.update_prefix_database(pdb)
        solver = SpfSolver(root, backend="device")
        digests: List[int] = []
        for i in range(rounds):
            if i > 0:
                node = apply_mutation(dbs, spec, i)
                ls.update_adjacency_database(dbs[node])
            if not every or (i + 1) % every != 0:
                continue
            graph = compile_ell(ls)
            srcs = ell_source_batch(graph, ls, root)
            packed = np.asarray(
                ell_view_batch_packed(graph, srcs)
            ).astype(np.int32)
            fleet_preload_views(ls, [(graph, srcs, packed)])
            ddb = solver.build_route_db(root, {"0": ls}, pfx)
            blob = wire.dumps(ddb.to_route_db(root))
            h = 0x811C9DC5
            for b in blob:
                h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
            digests.append(h)
        out[spec.tenant_id] = digests
    return out


def harvest(procs) -> List[Dict]:
    """Join spawned clients and load their result files; a child that
    died without writing is reported as an error record."""
    import json as _json
    import os

    results = []
    for p, out_path in procs:
        p.join(timeout=300)
        if p.is_alive():
            p.terminate()
            results.append(
                {"client_id": out_path, "errors": ["timeout"]}
            )
            continue
        if not os.path.exists(out_path):
            results.append({
                "client_id": out_path,
                "errors": [f"no result file (exit {p.exitcode})"],
            })
            continue
        with open(out_path) as f:
            results.append(_json.load(f))
    return results


_KINDS = ("grid", "ring", "mesh")
_SLOS = ("premium", "standard", "bulk")


def fleet_specs(
    clients: int, tenants_per_client: int, size: int = 4
) -> Dict[str, List[TenantSpec]]:
    """Deterministic client->tenants layout for the fleet mode:
    topology kinds and SLO classes rotate so every class exercises
    placement."""
    out: Dict[str, List[TenantSpec]] = {}
    n = 0
    for c in range(clients):
        specs = []
        for t in range(tenants_per_client):
            specs.append(TenantSpec(
                tenant_id=f"c{c}_t{t}",
                kind=_KINDS[n % len(_KINDS)],
                size=size,
                seed=n + 1,
                slo=_SLOS[n % len(_SLOS)],
            ))
            n += 1
        out[f"c{c}"] = specs
    return out


def main(argv: List[str] = None) -> int:
    """``python -m openr_tpu.load.multi_client --services N`` — bring
    up a ``FleetController`` fleet of N services (each with a hot
    standby unless ``--no-standby``), admit the tenant population by
    SLO class, drive it from spawned jax-free client processes, and
    gate every per-round view digest against the sequential oracle.
    Exit 0 only on full parity with zero client errors."""
    import argparse
    import os
    import tempfile

    ap = argparse.ArgumentParser(prog="multi_client")
    ap.add_argument("--services", type=int, default=2)
    ap.add_argument("--clients", type=int, default=2)
    ap.add_argument("--tenants-per-client", type=int, default=2)
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--size", type=int, default=4)
    ap.add_argument("--no-standby", action="store_true")
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)

    from openr_tpu.fleet import FleetController

    fc = FleetController(
        services=args.services,
        with_standby=not args.no_standby,
    )
    fc.start()
    report = {"ok": False, "services": args.services}
    try:
        ctrl_port = fc.serve_ctrl("127.0.0.1")
        client_specs = fleet_specs(
            args.clients, args.tenants_per_client, args.size
        )
        endpoints = {}
        for specs in client_specs.values():
            for s in specs:
                host, port = fc.admit(s.tenant_id, s.slo)
                endpoints[s.tenant_id] = [host, port]
        default_ep = next(iter(endpoints.values()))
        with tempfile.TemporaryDirectory() as td:
            procs = spawn_clients(
                default_ep[0], default_ep[1], client_specs,
                args.rounds, td,
                endpoints=endpoints,
                controller=["127.0.0.1", ctrl_port],
            )
            results = harvest(procs)
        all_specs = [
            s for specs in client_specs.values() for s in specs
        ]
        oracle = oracle_digests(all_specs, args.rounds)
        errors = [e for r in results for e in r.get("errors", [])]
        mismatches = []
        for r in results:
            for tid, digs in r.get("digests", {}).items():
                if digs != oracle.get(tid):
                    mismatches.append(tid)
        report.update({
            "ok": not errors and not mismatches,
            "tenants": len(endpoints),
            "errors": errors,
            "digest_mismatches": mismatches,
            "placement": fc.placement(),
            "counters": fc.counters(),
        })
    finally:
        fc.stop()
    text = json.dumps(report, indent=2, sort_keys=True, default=str)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    print(text)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
