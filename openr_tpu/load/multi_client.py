"""Multi-process client driver for the solver service.

The PR 8 load harness drives one in-process pipeline; this module
drives a ``SolverService`` the way production would be driven — N
OS-process client daemons, each owning a disjoint set of tenants,
registering worlds, churning metrics, and soliciting views over the
ctrl wire. Child processes are JAX-FREE (serve/client.py + the
topology generators only), so spawn startup is milliseconds and the
one device owner stays the service process.

Everything is deterministic from the spec: the world a tenant
registers and the metric it churns on round ``i`` derive only from
``(spec, i)``, so a parent (test or smoke gate) replays the same
schedule host-side to produce oracle digests without any channel back
from the children beyond the result files.

``run_client`` is module-level and takes only picklable arguments —
required by the ``spawn`` start method (the only safe method with a
jax parent).
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, replace
from typing import Dict, List, Tuple


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's deterministic world + churn schedule."""

    tenant_id: str
    kind: str          # "grid" | "ring" | "mesh"
    size: int
    seed: int = 0
    slo: str = "standard"

    def build_dbs(self) -> Dict[str, "object"]:
        from openr_tpu.models import topologies

        if self.kind == "grid":
            topo = topologies.grid(self.size)
        elif self.kind == "ring":
            topo = topologies.ring(self.size)
        elif self.kind == "mesh":
            topo = topologies.random_mesh(
                self.size, 3, seed=self.seed or 7
            )
        else:
            raise ValueError(f"unknown topology kind {self.kind!r}")
        return dict(topo.adj_dbs)

    def root_of(self, dbs: Dict) -> str:
        return sorted(dbs)[0]

    def mutation(self, dbs: Dict, round_i: int) -> Tuple[str, object]:
        """The round's churn: ONE adjacency metric bump on a
        deterministically chosen node. Returns (node, new_db); pure —
        parent oracles replay it bit-identically."""
        names = sorted(dbs)
        node = names[(round_i * 3 + self.seed) % len(names)]
        db = dbs[node]
        adjs = list(db.adjacencies)
        if not adjs:
            node = names[0]
            db = dbs[node]
            adjs = list(db.adjacencies)
        ai = (round_i + self.seed) % len(adjs)
        metric = 1 + ((round_i * 7 + self.seed * 5 + ai) % 13)
        adjs[ai] = replace(adjs[ai], metric=metric)
        return node, replace(db, adjacencies=tuple(adjs))


def apply_mutation(dbs: Dict, spec: TenantSpec, round_i: int) -> str:
    """Mutate ``dbs`` in place per the schedule; returns the node."""
    node, db = spec.mutation(dbs, round_i)
    dbs[node] = db
    return node


def run_client(
    host: str,
    port: int,
    client_id: str,
    specs: List[Dict],
    rounds: int,
    out_path: str,
    ksp2_every: int = 0,
    hold_open_s: float = 0.0,
) -> None:
    """Child-process entry: drive ``specs``' tenants for ``rounds``
    churn rounds and write a JSON result file — per-request latencies
    (by SLO class), the per-tenant view digest after every round, and
    any errors. ``ksp2_every > 0`` also solicits the second-path view
    every that-many rounds (digested as the JSON text of the reply).
    ``hold_open_s`` keeps the connection (and its tenants) alive after
    the last round — the disconnect tests use it."""
    from openr_tpu.serve.client import SolverClient

    result = {
        "client_id": client_id,
        "latencies_ms": {},
        "digests": {},
        "ksp2": {},
        "errors": [],
        "rounds": 0,
        "trace_id": None,
        "span_ids": [],
    }
    try:
        client = SolverClient(host, port)
        # reported back so the parent gate can check cross-wire trace
        # continuity: these ids must surface in the SERVICE's wave
        # flight records
        result["trace_id"] = client.trace_id
        worlds = {}
        for sd in specs:
            spec = TenantSpec(**sd)
            dbs = spec.build_dbs()
            worlds[spec.tenant_id] = (spec, dbs)
            client.register(spec.tenant_id, slo=spec.slo)
            client.update_world(
                spec.tenant_id, [dbs[k] for k in sorted(dbs)],
                root=spec.root_of(dbs),
            )
            result["digests"][spec.tenant_id] = []
            result["ksp2"][spec.tenant_id] = []
        for i in range(rounds):
            for tid, (spec, dbs) in worlds.items():
                if i > 0:
                    node = apply_mutation(dbs, spec, i)
                    client.update_world(tid, [dbs[node]])
                t0 = time.perf_counter()
                view = client.solve(tid)
                ms = (time.perf_counter() - t0) * 1000.0
                result["latencies_ms"].setdefault(
                    spec.slo, []
                ).append(ms)
                result["digests"][tid].append(view.digest())
                if ksp2_every and (i + 1) % ksp2_every == 0:
                    paths = client.ksp2(
                        tid, sorted(view.nodes[:8])
                    )
                    result["ksp2"][tid].append(
                        _digest_text(json.dumps(paths, sort_keys=True))
                    )
            result["rounds"] = i + 1
        result["span_ids"] = list(client.span_ids)
        if hold_open_s > 0:
            time.sleep(hold_open_s)
        client.close()
    except Exception as exc:  # noqa: BLE001 - reported in the artifact
        result["errors"].append(repr(exc))
    with open(out_path, "w") as f:
        json.dump(result, f)


def _digest_text(text: str) -> int:
    h = 0x811C9DC5
    for b in text.encode("utf-8"):
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


def spawn_clients(
    host: str,
    port: int,
    client_specs: Dict[str, List[TenantSpec]],
    rounds: int,
    out_dir: str,
    ksp2_every: int = 0,
    hold_open_s: float = 0.0,
):
    """Launch one spawn-context process per client; returns
    ``[(proc, out_path)]`` for the parent to join and harvest."""
    import multiprocessing as mp
    import os

    ctx = mp.get_context("spawn")
    procs = []
    for client_id, specs in client_specs.items():
        out_path = os.path.join(
            out_dir, f"solver_client_{client_id}.json"
        )
        p = ctx.Process(
            target=run_client,
            args=(
                host, port, client_id,
                [asdict(s) for s in specs], rounds, out_path,
            ),
            kwargs=dict(
                ksp2_every=ksp2_every, hold_open_s=hold_open_s
            ),
            daemon=True,
        )
        p.start()
        procs.append((p, out_path))
    return procs


def oracle_digests(
    specs: List[TenantSpec], rounds: int
) -> Dict[str, List[int]]:
    """Sequential single-graph oracle for the exact schedule
    ``run_client`` drives: per tenant, per round, the FNV digest of
    ``ell_view_batch_packed`` over the replayed world. Imports jax —
    parent/gate side only."""
    import numpy as np

    from openr_tpu.graph.linkstate import LinkState
    from openr_tpu.ops.spf_sparse import (
        compile_ell,
        ell_source_batch,
        ell_view_batch_packed,
    )

    out: Dict[str, List[int]] = {}
    for spec in specs:
        dbs = spec.build_dbs()
        ls = LinkState(area="0")
        for name in sorted(dbs):
            ls.update_adjacency_database(dbs[name])
        root = spec.root_of(dbs)
        digests = []
        for i in range(rounds):
            if i > 0:
                node = apply_mutation(dbs, spec, i)
                ls.update_adjacency_database(dbs[node])
            graph = compile_ell(ls)
            srcs = ell_source_batch(graph, ls, root)
            packed = np.asarray(
                ell_view_batch_packed(graph, srcs)
            ).astype(np.int32)
            h = 0x811C9DC5
            for b in packed.tobytes():
                h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
            digests.append(h)
        out[spec.tenant_id] = digests
    return out


def harvest(procs) -> List[Dict]:
    """Join spawned clients and load their result files; a child that
    died without writing is reported as an error record."""
    import json as _json
    import os

    results = []
    for p, out_path in procs:
        p.join(timeout=300)
        if p.is_alive():
            p.terminate()
            results.append(
                {"client_id": out_path, "errors": ["timeout"]}
            )
            continue
        if not os.path.exists(out_path):
            results.append({
                "client_id": out_path,
                "errors": [f"no result file (exit {p.exitcode})"],
            })
            continue
        with open(out_path) as f:
            results.append(_json.load(f))
    return results
