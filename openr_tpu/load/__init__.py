"""Service plane: sustained-load generation + admission/backpressure.

Two halves (see docs/ARCHITECTURE.md "Service plane"):

- ``admission`` — daemon-side backpressure: the rate-adaptive debounce
  controller and shed-by-coalescing publication admission Decision wires
  into its consume path.
- ``generator`` / ``harness`` — the load half: a seedable open-loop
  KvStore event generator and the closed-loop harness that drives the
  real KvStore→Decision→Fib pipeline at a target events/s, measures
  p50/p95/p99 e2e from the trace spine, and binary-searches the max
  sustainable rate against a p99 SLO.

``harness`` is imported lazily (``openr_tpu.load.harness``) because it
depends on the Decision/Fib modules; importing this package from inside
``decision`` must stay cycle-free.
"""

from openr_tpu.load.admission import (
    AdmissionConfig,
    AdmissionControl,
    CoalescedBatch,
    DebounceController,
    coalesce_publications,
)
from openr_tpu.load.generator import (
    FAULT_LOAD_GENERATOR,
    EventMix,
    LoadEvent,
    LoadGenerator,
)

__all__ = [
    "AdmissionConfig",
    "AdmissionControl",
    "CoalescedBatch",
    "DebounceController",
    "coalesce_publications",
    "FAULT_LOAD_GENERATOR",
    "EventMix",
    "LoadEvent",
    "LoadGenerator",
]
