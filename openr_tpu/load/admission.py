"""Admission control / backpressure for the Decision consume path.

Two cooperating mechanisms keep the daemon healthy when the KvStore
publication stream outruns the solve rate:

- ``DebounceController`` — a small hysteresis FSM that widens Decision's
  debounce ceiling (so bursts fold into fewer fused dispatches) while the
  reader backlog is deep, and narrows it back once the backlog drains.

- ``coalesce_backlog`` — shed-by-coalescing: drain the reader's backlog
  and squash it into one net-effect publication per area, dropping
  superseded per-key versions. This is *never* a semantic change: every
  KvStore key's value fully replaces the per-(node, key) state inside
  Decision (adjacency DBs, per-prefix entries, fibtime), so replaying
  only the last value per key yields the same LinkState/PrefixState —
  and therefore a bit-identical RouteDatabase — as the full replay.
  ``tests/test_sustained_load.py`` enforces this oracle parity.

Neither mechanism ever drops net effect; both only reduce *work*.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from openr_tpu.telemetry import get_registry
from openr_tpu.types import Publication


@dataclass
class AdmissionConfig:
    """Knobs for Decision's admission path. Defaults are conservative:
    shedding only engages with a real backlog (depth ≥ shed_depth), so a
    lightly-loaded daemon behaves exactly as before."""

    # backlog depth at which the consume path drains + coalesces
    shed_depth: int = 8
    # DebounceController band: widen at ≥ high, narrow at ≤ low
    widen_depth: int = 8
    narrow_depth: int = 2
    # debounce ceiling range: base is the configured debounce_max;
    # the controller may widen up to cap_s under sustained backlog
    cap_s: float = 2.0
    # skip the (overlap-only) solver prewarm when the backlog is deeper
    # than this: under pressure the fused rebuild will re-patch anyway,
    # and per-publication prewarm dispatch becomes pure overhead
    prewarm_depth_limit: int = 4


class DebounceController:
    """Rate-adaptive debounce ceiling: ``observe(depth)`` once per
    delivered publication.

    FSM over the current ceiling ``cur``:

        depth >= widen_depth  and cur < cap   -> WIDEN  (cur = min(2*cur, cap))
        depth <= narrow_depth and cur > base  -> NARROW (cur = max(cur/2, base))
        otherwise                             -> STEADY (hysteresis band)

    The ceiling is pushed into the AsyncDebounce via ``set_max_backoff``;
    counters ``decision.debounce_widenings`` / ``_narrowings`` and the
    ``decision.debounce_max_ms`` gauge make the FSM observable.

    The widen/narrow band also SELF-ADJUSTS from the admission
    counters it used to be hand-picked against: every ``tune_period``
    observations the controller samples ``{prefix}.admission.sheds``
    and ``{prefix}.admission.pubs_coalesced`` — sheds while inside the
    band mean widening engaged too late (``widen_depth`` steps down
    toward the backlog the shed path actually saw), a fully quiet
    period relaxes it back up toward the configured value. Adjustments
    are one step per period with the band floor pinned at
    ``narrow_depth + 1`` (the FSM's hysteresis invariant), counted in
    ``{prefix}.debounce_band_adjustments``. ``self_tune=False``
    restores the fixed hand-picked band.
    """

    WIDEN = "widen"
    NARROW = "narrow"
    STEADY = "steady"

    def __init__(
        self,
        base_max_s: float,
        cap_s: float,
        widen_depth: int = 8,
        narrow_depth: int = 2,
        debounce=None,
        metric_prefix: str = "decision",
        self_tune: bool = True,
        tune_period: int = 64,
    ):
        assert cap_s >= base_max_s > 0
        assert widen_depth > narrow_depth >= 0
        self._base = base_max_s
        self._cap = cap_s
        self._widen_depth = widen_depth
        self._widen_depth_base = widen_depth
        self._narrow_depth = narrow_depth
        self._debounce = debounce
        self._prefix = metric_prefix
        self._self_tune = self_tune
        self._tune_period = max(1, tune_period)
        self._observations = 0
        # (sheds, pubs_coalesced) at the last retune; None until the
        # first period completes so a fresh controller never adjusts
        # off counter history it did not witness
        self._tune_sample = None
        self.current_max_s = base_max_s
        get_registry().gauge(
            f"{metric_prefix}.debounce_max_ms",
            lambda: self.current_max_s * 1000.0,
        )

    @property
    def widen_depth(self) -> int:
        return self._widen_depth

    def _retune(self) -> None:
        reg = get_registry()
        sample = (
            reg.counter_get(f"{self._prefix}.admission.sheds"),
            reg.counter_get(f"{self._prefix}.admission.pubs_coalesced"),
        )
        prev, self._tune_sample = self._tune_sample, sample
        if prev is None:
            return
        sheds = sample[0] - prev[0]
        coalesced = sample[1] - prev[1]
        floor = self._narrow_depth + 1
        if sheds > 0 and self._widen_depth > floor:
            # backlogs reached the shed path while the ceiling was
            # still narrow: engage widening earlier
            self._widen_depth -= 1
        elif (
            sheds == 0
            and coalesced == 0
            and self._widen_depth < self._widen_depth_base
        ):
            # a full period with no pressure at all: relax back toward
            # the configured band
            self._widen_depth += 1
        else:
            return
        get_registry().counter_bump(
            f"{self._prefix}.debounce_band_adjustments"
        )

    def observe(self, depth: int) -> str:
        """Feed one backlog-depth sample; returns the action taken."""
        if self._self_tune:
            self._observations += 1
            if self._observations % self._tune_period == 0:
                self._retune()
        if depth >= self._widen_depth and self.current_max_s < self._cap:
            self.current_max_s = min(self.current_max_s * 2.0, self._cap)
            self._apply()
            get_registry().counter_bump(f"{self._prefix}.debounce_widenings")
            return self.WIDEN
        if depth <= self._narrow_depth and self.current_max_s > self._base:
            self.current_max_s = max(self.current_max_s / 2.0, self._base)
            self._apply()
            get_registry().counter_bump(f"{self._prefix}.debounce_narrowings")
            return self.NARROW
        return self.STEADY

    def _apply(self) -> None:
        if self._debounce is not None:
            self._debounce.set_max_backoff(self.current_max_s)


@dataclass
class CoalescedBatch:
    """Result of shed-by-coalescing one consume round."""

    # net-effect publications, one per area, in first-seen area order
    publications: List[Publication] = field(default_factory=list)
    # every drained publication's trace, arrival-ordered (first = oldest)
    traces: List[object] = field(default_factory=list)
    pubs_in: int = 0
    keys_in: int = 0
    keys_out: int = 0

    @property
    def keys_shed(self) -> int:
        return self.keys_in - self.keys_out


def coalesce_publications(pubs: List[Publication]) -> CoalescedBatch:
    """Squash an arrival-ordered publication backlog into one net-effect
    publication per area.

    Per area, replayed in order: a later value for a key supersedes the
    earlier one (KvStore floods only merge-accepted — strictly better —
    values, so last-wins matches ``compare_values`` order); an expiry
    cancels a pending value and vice versa. The output preserves exactly
    the final per-key state the full replay would have left behind.
    """
    batch = CoalescedBatch(pubs_in=len(pubs))
    merged: Dict[str, Dict[str, object]] = {}  # area -> key -> Value
    expired: Dict[str, Dict[str, None]] = {}  # area -> ordered key set
    area_order: List[str] = []
    for pub in pubs:
        if pub.area not in merged:
            merged[pub.area] = {}
            expired[pub.area] = {}
            area_order.append(pub.area)
        kv = merged[pub.area]
        exp = expired[pub.area]
        batch.keys_in += len(pub.key_vals) + len(pub.expired_keys)
        for key, value in pub.key_vals.items():
            kv[key] = value
            exp.pop(key, None)
        for key in pub.expired_keys:
            exp[key] = None
            kv.pop(key, None)
        if pub.trace is not None:
            batch.traces.append(pub.trace)
    for area in area_order:
        batch.keys_out += len(merged[area]) + len(expired[area])
        batch.publications.append(
            Publication(
                key_vals=merged[area],
                expired_keys=list(expired[area]),
                area=area,
            )
        )
    return batch


class AdmissionControl:
    """Decision-side admission path: owns the debounce FSM and the
    shed-by-coalescing drain. One instance per Decision module."""

    def __init__(
        self,
        config: Optional[AdmissionConfig] = None,
        metric_prefix: str = "decision",
    ):
        self.config = config or AdmissionConfig()
        self._prefix = metric_prefix
        self.controller: Optional[DebounceController] = None

    def bind_debounce(self, debounce, base_max_s: float) -> None:
        """Wire the controller to the module's AsyncDebounce (called by
        Decision once the debounce exists)."""
        self.controller = DebounceController(
            base_max_s=base_max_s,
            cap_s=max(self.config.cap_s, base_max_s),
            widen_depth=self.config.widen_depth,
            narrow_depth=self.config.narrow_depth,
            debounce=debounce,
            metric_prefix=self._prefix,
        )

    def admit(self, first_pub: Publication, reader) -> CoalescedBatch:
        """One consume round: observe backlog depth, adapt the debounce
        ceiling, and — only when the backlog is at/over ``shed_depth`` —
        drain and coalesce it behind ``first_pub``."""
        depth = reader.size()
        if self.controller is not None:
            self.controller.observe(depth)
        if depth < self.config.shed_depth:
            batch = CoalescedBatch(
                publications=[first_pub], pubs_in=1
            )
            if first_pub.trace is not None:
                batch.traces.append(first_pub.trace)
            nkeys = len(first_pub.key_vals) + len(first_pub.expired_keys)
            batch.keys_in = batch.keys_out = nkeys
            return batch
        pubs = [first_pub]
        while True:
            try:
                nxt = reader.try_get()
            except Exception:  # QueueClosedError: treat as drained
                break
            if nxt is None:
                break
            pubs.append(nxt)
        batch = coalesce_publications(pubs)
        reg = get_registry()
        reg.counter_bump(f"{self._prefix}.admission.sheds")
        if batch.keys_shed:
            reg.counter_bump(
                f"{self._prefix}.admission.shed_keys", batch.keys_shed
            )
        if batch.pubs_in > len(batch.publications):
            reg.counter_bump(
                f"{self._prefix}.admission.pubs_coalesced",
                batch.pubs_in - len(batch.publications),
            )
        return batch

    def allow_prewarm(self, depth: int) -> bool:
        """Prewarm is an overlap-only optimization (never correctness);
        under a deep backlog the per-publication dispatch is pure
        overhead, so rate-gate it."""
        if depth <= self.config.prewarm_depth_limit:
            return True
        get_registry().counter_bump(
            f"{self._prefix}.admission.prewarm_skipped"
        )
        return False
