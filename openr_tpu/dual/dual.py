"""DUAL: Diffusing Update Algorithm (loop-free distributed shortest paths).

Behavioral parity with the reference ``openr/dual/Dual.{h,cpp}`` (EIGRP's
DUAL per the JJGLA'93 paper), which KvStore uses to constrain flooding to
a per-root spanning tree (reference: KvStore.h:202 DualNode inheritance):

- feasibility condition: a neighbor is adoptable by *local* computation
  only if its reported distance is strictly below the feasible distance
  AND it attains the current minimum (Dual.cpp:149 meetFeasibleCondition)
- otherwise a *diffusing* computation starts: the node freezes its
  reported distance at the value via its CURRENT successor (infinity if
  the successor died — this poisons downstream instead of counting up),
  queries every up neighbor, and stays ACTIVE until the last reply
  (Dual.cpp:214 diffusingComputation, :636 processReply)
- a query from the current successor received while passive joins the
  diffusion and defers its reply until convergence (the "cornet" stack);
  all other queries are answered immediately (Dual.cpp:597 processQuery)
- the ACTIVE0-3 sub-state machine tracks how the computation originated
  (Dual.cpp:20 DualStateMachine::processEvent)
- per-root trees: DualNode coordinates one Dual per root and elects the
  flood root as the smallest ready root id; sptPeers = {parent} ∪ children
  (children are registered by dependents via flood-topo messages)

Message types (reference: openr/if/Dual.thrift): UPDATE / QUERY / REPLY.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

INFINITY = (1 << 63) - 1


class DualMessageType(enum.IntEnum):
    UPDATE = 1
    QUERY = 2
    REPLY = 3


@dataclass
class DualMessage:
    """reference: openr/if/Dual.thrift:24 DualMessage."""

    dst_id: str  # the root this message concerns
    distance: int
    type: DualMessageType


# outgoing message batches: neighbor -> [messages]
MsgsToSend = Dict[str, List[DualMessage]]


class DualState(enum.IntEnum):
    """reference: Dual.h DualState."""

    ACTIVE0 = 0
    ACTIVE1 = 1
    ACTIVE2 = 2
    ACTIVE3 = 3
    PASSIVE = 4


class DualEvent(enum.IntEnum):
    """reference: Dual.h DualEvent."""

    QUERY_FROM_SUCCESSOR = 0
    LAST_REPLY = 1
    INCREASE_D = 2
    OTHERS = 3


class DualStateMachine:
    """reference: Dual.cpp:20 DualStateMachine::processEvent."""

    def __init__(self) -> None:
        self.state = DualState.PASSIVE

    def process_event(self, event: DualEvent, fc: bool = True) -> None:
        s, e = self.state, event
        if s == DualState.PASSIVE:
            if fc:
                return
            self.state = (
                DualState.ACTIVE3
                if e == DualEvent.QUERY_FROM_SUCCESSOR
                else DualState.ACTIVE1
            )
        elif s == DualState.ACTIVE0:
            if e == DualEvent.LAST_REPLY:
                self.state = DualState.PASSIVE if fc else DualState.ACTIVE2
        elif s == DualState.ACTIVE1:
            if e == DualEvent.INCREASE_D:
                self.state = DualState.ACTIVE0
            elif e == DualEvent.LAST_REPLY:
                self.state = DualState.PASSIVE
            elif e == DualEvent.QUERY_FROM_SUCCESSOR:
                self.state = DualState.ACTIVE2
        elif s == DualState.ACTIVE2:
            if e == DualEvent.LAST_REPLY:
                self.state = DualState.PASSIVE if fc else DualState.ACTIVE3
        elif s == DualState.ACTIVE3:
            if e == DualEvent.LAST_REPLY:
                self.state = DualState.PASSIVE
            elif e == DualEvent.INCREASE_D:
                self.state = DualState.ACTIVE2


@dataclass
class NeighborInfo:
    """reference: Dual.h NeighborInfo."""

    report_distance: int = INFINITY
    expect_reply: bool = False
    need_to_reply: bool = False


def _add(d1: int, d2: int) -> int:
    """Saturating add (reference: Dual.cpp:393 addDistances)."""
    if d1 == INFINITY or d2 == INFINITY:
        return INFINITY
    return d1 + d2


class Dual:
    """One node's DUAL instance for one root (reference: Dual.h:66)."""

    def __init__(
        self,
        node_id: str,
        root_id: str,
        local_distances: Optional[Dict[str, int]] = None,
        nexthop_change_cb: Optional[
            Callable[[Optional[str], Optional[str]], None]
        ] = None,
    ):
        self.node_id = node_id
        self.root_id = root_id
        self.local_distances: Dict[str, int] = dict(local_distances or {})
        self.neighbor_infos: Dict[str, NeighborInfo] = {
            n: NeighborInfo() for n in self.local_distances
        }
        self.sm = DualStateMachine()
        self._cb = nexthop_change_cb
        self.children_: Set[str] = set()
        # the reply-owed stack: queries whose replies are pending
        self.cornet: List[str] = []
        if node_id == root_id:
            self.distance = 0
            self.report_distance = 0
            self.feasible_distance = 0
            self.nexthop: Optional[str] = node_id
        else:
            self.distance = INFINITY
            self.report_distance = INFINITY
            self.feasible_distance = INFINITY
            self.nexthop = None

    # -- state helpers ----------------------------------------------------

    @property
    def state(self) -> DualState:
        return self.sm.state

    def _neighbor_up(self, neighbor: str) -> bool:
        return self.local_distances.get(neighbor, INFINITY) != INFINITY

    def _set_nexthop(self, new_nh: Optional[str]) -> None:
        if new_nh != self.nexthop:
            old = self.nexthop
            self.nexthop = new_nh
            if self._cb is not None:
                self._cb(old, new_nh)

    def get_min_distance(self) -> int:
        """reference: Dual.cpp:84 getMinDistance."""
        if self.node_id == self.root_id:
            return 0
        dmin = INFINITY
        for n, ld in self.local_distances.items():
            rd = self.neighbor_infos[n].report_distance
            dmin = min(dmin, _add(ld, rd))
        return dmin

    def route_affected(self) -> bool:
        """reference: Dual.cpp:100 routeAffected."""
        if not self.local_distances:
            return False
        if self.nexthop == self.node_id:
            return False  # I am the root
        dmin = self.get_min_distance()
        if self.distance != dmin:
            return True
        if dmin == INFINITY:
            return False  # no valid route, nothing new
        if self.nexthop is None:
            return True
        # nexthop no longer on a min-distance path?
        min_nexthops = {
            n
            for n, ld in self.local_distances.items()
            if _add(ld, self.neighbor_infos[n].report_distance) == dmin
        }
        return self.nexthop not in min_nexthops

    def meet_feasible_condition(self) -> Tuple[bool, Optional[str], int]:
        """FC: some up neighbor with rd < FD attaining the minimum.
        reference: Dual.cpp:149 meetFeasibleCondition."""
        dmin = self.get_min_distance()
        for n in sorted(self.local_distances):
            ld = self.local_distances[n]
            if ld == INFINITY:
                continue
            rd = self.neighbor_infos[n].report_distance
            if rd < self.feasible_distance and _add(ld, rd) == dmin:
                return True, n, dmin
        return False, None, dmin

    # -- message emission -------------------------------------------------

    def _emit(self, msgs: MsgsToSend, neighbor: str,
              mtype: DualMessageType, distance: int) -> None:
        msgs.setdefault(neighbor, []).append(
            DualMessage(dst_id=self.root_id, distance=distance, type=mtype)
        )

    def flood_updates(self, msgs: MsgsToSend) -> None:
        """reference: Dual.cpp:172 floodUpdates."""
        for n, ld in self.local_distances.items():
            if ld == INFINITY:
                continue
            self._emit(msgs, n, DualMessageType.UPDATE, self.report_distance)

    def send_reply(self, msgs: MsgsToSend) -> None:
        """Pop the reply-owed stack (reference: Dual.cpp:567 sendReply)."""
        assert self.cornet, "send_reply with empty cornet"
        dst = self.cornet.pop()
        if not self._neighbor_up(dst):
            # owed a reply but the link is down on our end: defer until
            # the link comes back (peerUp flushes need_to_reply)
            self.neighbor_infos.setdefault(dst, NeighborInfo()).need_to_reply = True
            return
        self._emit(msgs, dst, DualMessageType.REPLY, self.report_distance)

    # -- computations -----------------------------------------------------

    def local_computation(
        self, new_nexthop: str, new_distance: int, msgs: MsgsToSend
    ) -> None:
        """reference: Dual.cpp:192 localComputation."""
        same_rd = new_distance == self.report_distance
        self._set_nexthop(new_nexthop)
        self.distance = new_distance
        self.report_distance = new_distance
        self.feasible_distance = new_distance
        if not same_rd:
            self.flood_updates(msgs)

    def diffusing_computation(self, msgs: MsgsToSend) -> bool:
        """Freeze the reported distance at the value via the CURRENT
        successor (infinity when it died — poisoning downstream rather
        than counting up) and query all up neighbors.
        reference: Dual.cpp:214 diffusingComputation."""
        assert self.nexthop is not None
        ld = self.local_distances.get(self.nexthop, INFINITY)
        rd = self.neighbor_infos[self.nexthop].report_distance
        new_distance = _add(ld, rd)
        self.distance = new_distance
        self.report_distance = new_distance
        self.feasible_distance = new_distance

        success = False
        for n, cost in self.local_distances.items():
            if cost == INFINITY:
                continue
            self._emit(msgs, n, DualMessageType.QUERY, self.report_distance)
            self.neighbor_infos[n].expect_reply = True
            success = True
        return success

    def try_local_or_diffusing(
        self, event: DualEvent, need_reply: bool, msgs: MsgsToSend
    ) -> None:
        """reference: Dual.cpp:249 tryLocalOrDiffusing."""
        if not self.route_affected():
            if need_reply:
                self.send_reply(msgs)
            return
        fc, new_nh, new_dist = self.meet_feasible_condition()
        if fc:
            self.local_computation(new_nh, new_dist, msgs)
            if need_reply:
                self.send_reply(msgs)
        else:
            if need_reply and event != DualEvent.QUERY_FROM_SUCCESSOR:
                # queries from non-successors are answered before diffusing
                self.send_reply(msgs)
            if self.nexthop is None:
                # nowhere to even freeze a distance from: unreachable
                self.distance = INFINITY
                self.report_distance = INFINITY
                self.feasible_distance = INFINITY
                return
            if self.diffusing_computation(msgs):
                self.sm.process_event(event, False)
            if self.nexthop is not None and not self._neighbor_up(self.nexthop):
                self._set_nexthop(None)

    # -- peer events ------------------------------------------------------

    def peer_up(self, neighbor: str, cost: int, msgs: MsgsToSend) -> None:
        """reference: Dual.cpp:401 peerUp."""
        if self.nexthop == neighbor:
            # non-graceful bounce: as-if a peer-down had happened first
            self._set_nexthop(None)
            self.distance = INFINITY
        self.local_distances[neighbor] = cost
        self.neighbor_infos.setdefault(neighbor, NeighborInfo())

        if self.sm.state == DualState.PASSIVE:
            self.try_local_or_diffusing(DualEvent.OTHERS, False, msgs)
        else:
            if self.neighbor_infos[neighbor].expect_reply:
                # the neighbor we awaited came back: treat as its reply
                self.process_reply(
                    neighbor,
                    DualMessage(
                        dst_id=self.root_id,
                        distance=self.neighbor_infos[neighbor].report_distance,
                        type=DualMessageType.REPLY,
                    ),
                    msgs,
                )
        # introduce ourselves
        self._emit(msgs, neighbor, DualMessageType.UPDATE,
                   self.report_distance)
        if self.neighbor_infos[neighbor].need_to_reply:
            self.neighbor_infos[neighbor].need_to_reply = False
            self._emit(msgs, neighbor, DualMessageType.REPLY,
                       self.report_distance)

    def peer_down(self, neighbor: str, msgs: MsgsToSend) -> None:
        """reference: Dual.cpp:466 peerDown."""
        self.remove_child(neighbor)
        self.local_distances[neighbor] = INFINITY
        info = self.neighbor_infos.setdefault(neighbor, NeighborInfo())
        info.report_distance = INFINITY
        if self.sm.state == DualState.PASSIVE:
            self.try_local_or_diffusing(DualEvent.INCREASE_D, False, msgs)
        else:
            self.sm.process_event(DualEvent.INCREASE_D)
            if info.expect_reply:
                # a dead neighbor's reply is an implicit infinity reply
                self.process_reply(
                    neighbor,
                    DualMessage(
                        dst_id=self.root_id,
                        distance=INFINITY,
                        type=DualMessageType.REPLY,
                    ),
                    msgs,
                )

    def peer_cost_change(self, neighbor: str, cost: int,
                         msgs: MsgsToSend) -> None:
        """reference: Dual.cpp:505 peerCostChange."""
        event = (
            DualEvent.INCREASE_D
            if cost > self.local_distances.get(neighbor, INFINITY)
            else DualEvent.OTHERS
        )
        self.local_distances[neighbor] = cost
        self.neighbor_infos.setdefault(neighbor, NeighborInfo())
        if self.sm.state == DualState.PASSIVE:
            self.try_local_or_diffusing(event, False, msgs)
        else:
            if self.nexthop == neighbor:
                self.distance = _add(
                    cost, self.neighbor_infos[neighbor].report_distance
                )
            self.sm.process_event(event)

    # -- message processing -----------------------------------------------

    def process_update(self, neighbor: str, msg: DualMessage,
                       msgs: MsgsToSend) -> None:
        """reference: Dual.cpp:530 processUpdate."""
        self.neighbor_infos.setdefault(
            neighbor, NeighborInfo()
        ).report_distance = msg.distance
        if neighbor not in self.local_distances:
            return  # UPDATE before LINK-UP
        if self.sm.state == DualState.PASSIVE:
            self.try_local_or_diffusing(DualEvent.OTHERS, False, msgs)
        else:
            if self.nexthop == neighbor:
                self.distance = _add(
                    self.local_distances[neighbor], msg.distance
                )
            self.sm.process_event(DualEvent.OTHERS)

    def process_query(self, neighbor: str, msg: DualMessage,
                      msgs: MsgsToSend) -> None:
        """reference: Dual.cpp:597 processQuery."""
        self.neighbor_infos.setdefault(
            neighbor, NeighborInfo()
        ).report_distance = msg.distance
        self.cornet.append(neighbor)
        event = (
            DualEvent.QUERY_FROM_SUCCESSOR
            if self.nexthop == neighbor
            else DualEvent.OTHERS
        )
        if self.sm.state == DualState.PASSIVE:
            self.try_local_or_diffusing(event, True, msgs)
        else:
            if self.nexthop == neighbor:
                self.distance = _add(
                    self.local_distances.get(neighbor, INFINITY),
                    self.neighbor_infos[neighbor].report_distance,
                )
            self.sm.process_event(event)
            self.send_reply(msgs)

    def process_reply(self, neighbor: str, msg: DualMessage,
                      msgs: MsgsToSend) -> None:
        """reference: Dual.cpp:636 processReply."""
        info = self.neighbor_infos.setdefault(neighbor, NeighborInfo())
        if not info.expect_reply:
            return  # late reply after we declared the link down: ignore
        info.report_distance = msg.distance
        info.expect_reply = False
        if any(i.expect_reply for i in self.neighbor_infos.values()):
            return
        # last reply: free to pick the optimal successor; FD resets
        self.sm.process_event(DualEvent.LAST_REPLY, True)
        dmin = INFINITY
        new_nh: Optional[str] = None
        for n in sorted(self.local_distances):
            d = _add(
                self.local_distances[n],
                self.neighbor_infos[n].report_distance,
            )
            if d < dmin:
                dmin = d
                new_nh = n
        same_rd = dmin == self.report_distance
        self.distance = dmin
        self.report_distance = dmin
        self.feasible_distance = dmin
        self._set_nexthop(new_nh)
        if not same_rd:
            self.flood_updates(msgs)
        if self.cornet:
            self.send_reply(msgs)

    # -- spanning tree ----------------------------------------------------

    def add_child(self, child: str) -> None:
        """reference: Dual.cpp:337 addChild."""
        self.children_.add(child)

    def remove_child(self, child: str) -> None:
        self.children_.discard(child)

    def children(self) -> Set[str]:
        return set(self.children_)

    def has_valid_route(self) -> bool:
        return (
            self.sm.state == DualState.PASSIVE
            and self.nexthop is not None
            and self.distance < INFINITY
        )

    def spt_peers(self) -> Set[str]:
        """Parent + children: the links flooding rides.
        reference: Dual.cpp:380 sptPeers."""
        if not self.has_valid_route():
            return set()
        peers = self.children()
        peers.add(self.nexthop)
        return peers


class DualNode:
    """Multi-root coordinator (reference: DualNode in Dual.h, which
    KvStoreDb inherits): one Dual per root, flood-root election as the
    smallest ready root id, message fan-in/out."""

    def __init__(
        self,
        node_id: str,
        is_root: bool = False,
        nexthop_change_cb: Optional[
            Callable[[str, Optional[str], Optional[str]], None]
        ] = None,
    ):
        self.node_id = node_id
        self.is_root = is_root
        self.duals: Dict[str, Dual] = {}
        self._peers: Dict[str, int] = {}
        self._cb = nexthop_change_cb
        if is_root:
            self._get_or_create(node_id)

    def _get_or_create(self, root_id: str) -> Dual:
        dual = self.duals.get(root_id)
        if dual is None:
            cb = None
            if self._cb is not None:
                cb = lambda old, new, root=root_id: self._cb(root, old, new)
            dual = self.duals[root_id] = Dual(
                self.node_id, root_id, dict(self._peers), cb
            )
        return dual

    # -- peer lifecycle ---------------------------------------------------

    def peer_up(self, neighbor: str, cost: int) -> MsgsToSend:
        self._peers[neighbor] = cost
        msgs: MsgsToSend = {}
        for dual in self.duals.values():
            dual.peer_up(neighbor, cost, msgs)
        return msgs

    def peer_down(self, neighbor: str) -> MsgsToSend:
        self._peers.pop(neighbor, None)
        msgs: MsgsToSend = {}
        for dual in self.duals.values():
            dual.peer_down(neighbor, msgs)
        return msgs

    def peer_cost_change(self, neighbor: str, cost: int) -> MsgsToSend:
        self._peers[neighbor] = cost
        msgs: MsgsToSend = {}
        for dual in self.duals.values():
            dual.peer_cost_change(neighbor, cost, msgs)
        return msgs

    # -- messages ---------------------------------------------------------

    def process_message(self, neighbor: str, msg: DualMessage) -> MsgsToSend:
        dual = self._get_or_create(msg.dst_id)
        msgs: MsgsToSend = {}
        if msg.type == DualMessageType.UPDATE:
            dual.process_update(neighbor, msg, msgs)
        elif msg.type == DualMessageType.QUERY:
            dual.process_query(neighbor, msg, msgs)
        elif msg.type == DualMessageType.REPLY:
            dual.process_reply(neighbor, msg, msgs)
        return msgs

    # -- introspection ----------------------------------------------------

    def get_dual(self, root_id: str) -> Optional[Dual]:
        return self.duals.get(root_id)

    def pick_flood_root(self) -> Optional[str]:
        """Smallest ready root id (reference: DualNode flood-root pick)."""
        candidates = [
            root
            for root, dual in self.duals.items()
            if dual.has_valid_route() or root == self.node_id
        ]
        return min(candidates) if candidates else None

    def spt_peers(self, root_id: str) -> Set[str]:
        dual = self.duals.get(root_id)
        if dual is None:
            return set()
        if self.node_id == root_id:
            return dual.children()
        return dual.spt_peers()
