"""RangeAllocator: distributed value election over the KvStore.

Behavioral parity with the reference ``openr/allocators/RangeAllocator``
(RangeAllocator.h:29): a node claims a value in [start, end] by
advertising ``<key_prefix><value> -> <node_name>``; the KvStore merge
ordering (version, then originatorId) is the consensus arbiter — two
same-version claims resolve deterministically to the higher node name,
and the loser detects the loss and proposes a different value with
backoff. Initial proposal is a deterministic hash of the node name so
disjoint nodes usually avoid collisions outright.
"""

from __future__ import annotations

import hashlib
import random
from typing import Callable, Optional, Tuple

from openr_tpu.types import Value
from openr_tpu.utils.eventbase import OpenrEventBase

# Claims are TTL'd so an abandoned allocator's key ages out of the
# flooded store instead of living forever
# (reference: Constants.h:195 kRangeAllocTtl = 5min).
RANGE_ALLOC_TTL_MS = 300_000

# a released claim's tombstone ages out fast so the value frees up in
# seconds, not kRangeAllocTtl
RELEASE_TOMBSTONE_TTL_MS = 1_000


class RangeAllocator:
    def __init__(
        self,
        evb: OpenrEventBase,
        kvstore_client,
        my_node_name: str,
        key_prefix: str,
        allocator_range: Tuple[int, int],
        callback: Callable[[Optional[int]], None],
        area: str = "0",
        retry_interval_s: float = 0.05,
        override_owner: bool = False,
        rand_seed: Optional[int] = None,
    ):
        self._evb = evb
        self._client = kvstore_client
        self._node = my_node_name
        self._key_prefix = key_prefix
        self._start, self._end = allocator_range
        assert self._end >= self._start
        self._callback = callback
        self._area = area
        self._retry_interval = retry_interval_s
        self._override_owner = override_owner
        self._rng = random.Random(
            rand_seed if rand_seed is not None else my_node_name
        )
        self._my_value: Optional[int] = None
        self._allocated = False
        self._stopped = False
        self._refresh_timer = None
        self._client.subscribe_key_filter(self._on_publication)

    # -- public -----------------------------------------------------------

    def start_allocator(self, init_value: Optional[int] = None) -> None:
        """reference: RangeAllocator.h:69 startAllocator."""
        value = (
            init_value
            if init_value is not None
            and self._start <= init_value <= self._end
            else self._initial_proposal()
        )
        self._evb.run_immediately_or_in_event_base(
            lambda: self._try_claim(value)
        )

    def stop(self) -> None:
        """Stop claiming: unsubscribe and best-effort release the claim
        so other nodes can re-elect the value immediately instead of
        waiting out RANGE_ALLOC_TTL_MS (reference:
        RangeAllocator-inl.h:75-86 stop — unsubscribeKey + unsetKey).
        Release = flood a short-TTL empty tombstone at a bumped
        version; _try_claim recognizes empty values as free. TTL expiry
        remains the fallback if the tombstone is lost."""
        self._stopped = True
        if self._refresh_timer is not None:
            self._refresh_timer.cancel()
            self._refresh_timer = None
        unsubscribe = getattr(
            self._client, "unsubscribe_key_filter", None
        )
        if unsubscribe is not None:
            unsubscribe(self._on_publication)
        # release on the EVENT BASE thread: the claim FSM (_try_claim's
        # get/set) runs there, so scheduling the release serializes it
        # after any in-flight claim write — otherwise a claim landing
        # just after a caller-thread release check would stay locked for
        # the full TTL. _my_value is read inside the closure, on the evb,
        # so an in-flight _try_claim's freshly-claimed value is seen.
        self._evb.run_immediately_or_in_event_base(self._release_claim)

    def _release_claim(self) -> None:
        value = self._my_value  # evb thread: serialized after claim FSM
        clear = getattr(self._client, "clear_key", None)
        if value is None or clear is None:
            return
        try:
            # only release a claim the LOCAL store says is ours — a
            # peer may have just won the tie-break. A winning claim
            # still in flight from another node can slip this check
            # (eventually-consistent store); the cost is one bounded
            # re-election flap on that node, traded against freeing
            # the value ~300x faster than TTL ageout on every clean
            # shutdown.
            stored = self._client.get_key(
                self._area, self._key_for(value)
            )
            if (
                stored is not None
                and stored.value == self._node.encode()
                and stored.originator_id == self._node
            ):
                clear(
                    self._area,
                    self._key_for(value),
                    b"",
                    ttl=RELEASE_TOMBSTONE_TTL_MS,
                )
        except Exception:
            pass  # best-effort; TTL expiry is the fallback

    def get_value(self) -> Optional[int]:
        return self._my_value if self._allocated else None

    def is_range_consumed(self) -> bool:
        """reference: RangeAllocator.h:90 isRangeConsumed."""
        owned = self._client.dump_all_with_prefix(self._area, self._key_prefix)
        return len(owned) >= (self._end - self._start + 1)

    # -- internals --------------------------------------------------------

    def _key_for(self, value: int) -> str:
        return f"{self._key_prefix}{value}"

    def _initial_proposal(self) -> int:
        size = self._end - self._start + 1
        digest = int.from_bytes(
            hashlib.sha256(self._node.encode()).digest()[:8], "big"
        )
        return self._start + digest % size

    def _try_claim(self, value: int) -> None:
        if self._stopped:
            return
        existing = self._client.get_key(self._area, self._key_for(value))
        # an empty value is a release tombstone (stop() above): the
        # value is free — claim PAST the tombstone's version
        tombstone = (
            existing is not None and existing.value == b""
        )
        foreign = (
            existing is not None
            and not tombstone
            and existing.value is not None
            and existing.value != self._node.encode()
        )
        if foreign and not self._override_owner:
            self._try_next(value)
            return
        self._my_value = value
        self._allocated = False
        # claim at the SAME version as a foreign owner: the merge ordering
        # breaks the tie by originator id, deterministically, on every
        # store in the network. Fresh keys start at version 1; a release
        # tombstone is outbid at version+1.
        version = existing.version if foreign else (
            1 if existing is None
            else existing.version + 1 if tombstone
            else existing.version
        )
        self._client.set_key(
            self._area,
            self._key_for(value),
            self._node.encode(),
            version=version,
            ttl=RANGE_ALLOC_TTL_MS,
        )
        self._evb.schedule_timeout(
            self._retry_interval, lambda: self._verify_claim(value)
        )

    def _verify_claim(self, value: int) -> None:
        if self._stopped or self._my_value != value:
            return
        stored = self._client.get_key(self._area, self._key_for(value))
        if (
            stored is not None
            and stored.value == self._node.encode()
            and stored.originator_id == self._node
        ):
            if not self._allocated:
                self._allocated = True
                self._start_ttl_refresh()
                self._callback(value)
        else:
            self._my_value = None
            self._try_next(value)

    def _start_ttl_refresh(self) -> None:
        """Keep our claim's TTL fresh while we own it. Deliberately NOT
        client.persist_key: ownership enforcement would bump the version
        to win the key back, overriding the same-version originator-id
        consensus that makes the allocator converge. A ttl-only refresh
        (bumped ttlVersion, value=None) preserves the merge ordering."""
        if self._refresh_timer is not None:
            return
        interval = RANGE_ALLOC_TTL_MS / 1000.0 / 3.0
        self._refresh_timer = self._evb.schedule_periodic(
            interval, self._refresh_claim_ttl, jitter_first=True
        )

    def _refresh_claim_ttl(self) -> None:
        if self._stopped or self._my_value is None or not self._allocated:
            return
        # not ours anymore -> no-op; the publication path handles the loss
        self._client.refresh_ttl(
            self._area, self._key_for(self._my_value), RANGE_ALLOC_TTL_MS
        )

    def _try_next(self, failed_value: int) -> None:
        if self._stopped:
            return
        size = self._end - self._start + 1
        step = 1 + self._rng.randrange(max(1, size // 8))
        nxt = self._start + (failed_value - self._start + step) % size
        self._evb.schedule_timeout(
            self._retry_interval, lambda: self._try_claim(nxt)
        )

    def _on_publication(self, area: str, key: str, value: Optional[Value]):
        if (
            self._stopped
            or area != self._area
            or self._my_value is None
            or key != self._key_for(self._my_value)
        ):
            return
        if value is None or value.value == b"":
            # true expiry (pub.expired_keys) or a peer's release
            # tombstone: the value is FREE — re-claim the same value
            # (moving to a different one would churn allocations, e.g.
            # a network-wide SR label change, for no reason)
            claimed = self._my_value
            self._evb.run_immediately_or_in_event_base(
                lambda: self._try_claim(claimed)
            )
            return
        if value.value is None:
            # ttl-only refresh (ours or a peer's): carries no ownership
            # information — NOT an expiry. Re-claiming here would churn
            # the allocation every refresh interval.
            return
        if value.value != self._node.encode():
            # a higher-precedence claim may have taken our value — but the
            # publication can be stale (an interleaved losing claim that
            # merged momentarily before ours). Confirm against the store.
            stored = self._client.get_key(self._area, key)
            if stored is not None and stored.value == self._node.encode():
                return  # stale: we still own it
            lost = self._my_value
            self._my_value = None
            was_allocated = self._allocated
            self._allocated = False
            if was_allocated:
                self._callback(None)
            self._try_next(lost)
