"""PrefixAllocator: plug-and-play per-node prefix assignment.

Behavioral parity with the reference ``openr/allocators/PrefixAllocator``
(PrefixAllocator.h:35): elects a unique sub-prefix index out of a seed
prefix via RangeAllocator consensus over the KvStore, advertises the
elected prefix through the PrefixManager, programs the address on the
loopback via netlink, and persists the allocation so restarts re-claim
the same index. Static mode assigns from a configured node->prefix map.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from openr_tpu.allocators.range_allocator import RangeAllocator
from openr_tpu.types import BinaryAddress, IpPrefix, PrefixEntry, PrefixType
from openr_tpu.utils.eventbase import OpenrEventBase

ALLOC_PREFIX_MARKER = "allocprefix:"  # reference: Constants kPrefixAllocMarker
PERSIST_KEY = "prefix-allocator-index"


def sub_prefix(seed: IpPrefix, alloc_len: int, index: int) -> IpPrefix:
    """Carve the index-th /alloc_len prefix out of the seed prefix."""
    assert alloc_len >= seed.prefix_length
    addr_bits = len(seed.prefix_address.addr) * 8
    base = int.from_bytes(seed.prefix_address.addr, "big")
    offset = index << (addr_bits - alloc_len)
    return IpPrefix(
        prefix_address=BinaryAddress(
            addr=(base | offset).to_bytes(addr_bits // 8, "big")
        ),
        prefix_length=alloc_len,
    )


class PrefixAllocator:
    def __init__(
        self,
        my_node_name: str,
        evb: OpenrEventBase,
        kvstore_client,
        prefix_manager,
        seed_prefix: Optional[IpPrefix] = None,
        alloc_prefix_len: int = 64,
        static_prefixes: Optional[Dict[str, IpPrefix]] = None,
        netlink=None,
        loopback_if: str = "lo",
        config_store=None,
        area: str = "0",
        on_allocated: Optional[Callable[[Optional[IpPrefix]], None]] = None,
    ):
        self._node = my_node_name
        self._evb = evb
        self._prefix_manager = prefix_manager
        self._netlink = netlink
        self._loopback_if = loopback_if
        self._config_store = config_store
        self._on_allocated = on_allocated
        self.allocated_prefix: Optional[IpPrefix] = None
        self._range_allocator: Optional[RangeAllocator] = None

        if static_prefixes is not None:
            # static mode: allocation comes straight from config
            prefix = static_prefixes.get(my_node_name)
            if prefix is not None:
                self._evb.run_in_event_base(lambda: self._apply(prefix))
            return

        assert seed_prefix is not None
        self._seed = seed_prefix
        self._alloc_len = alloc_prefix_len
        count = 1 << (alloc_prefix_len - seed_prefix.prefix_length)
        init_index = None
        if config_store is not None:
            init_index = config_store.load(PERSIST_KEY)
            if init_index is not None and not (0 <= init_index < count):
                init_index = None
        self._range_allocator = RangeAllocator(
            evb,
            kvstore_client,
            my_node_name,
            ALLOC_PREFIX_MARKER,
            (0, count - 1),
            self._on_index,
            area=area,
        )
        self._range_allocator.start_allocator(init_value=init_index)

    def stop(self) -> None:
        if self._range_allocator is not None:
            self._range_allocator.stop()

    # -- internals --------------------------------------------------------

    def _on_index(self, index: Optional[int]) -> None:
        if index is None:
            self._withdraw()
            return
        if self._config_store is not None:
            self._config_store.store(PERSIST_KEY, index)
        self._apply(sub_prefix(self._seed, self._alloc_len, index))

    def _apply(self, prefix: IpPrefix) -> None:
        self.allocated_prefix = prefix
        self._prefix_manager.advertise_prefixes(
            [
                PrefixEntry(
                    prefix=prefix, type=PrefixType.PREFIX_ALLOCATOR
                )
            ]
        )
        if self._netlink is not None:
            try:
                self._netlink.add_ifaddress(self._loopback_if, prefix)
            except Exception:
                pass
        if self._on_allocated is not None:
            self._on_allocated(prefix)

    def _withdraw(self) -> None:
        if self.allocated_prefix is not None:
            self._prefix_manager.withdraw_prefixes([self.allocated_prefix])
            self.allocated_prefix = None
        if self._on_allocated is not None:
            self._on_allocated(None)
