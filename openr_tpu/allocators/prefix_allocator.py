"""PrefixAllocator: plug-and-play per-node prefix assignment.

Behavioral parity with the reference ``openr/allocators/PrefixAllocator``
(PrefixAllocator.h:35, PrefixAllocator.cpp:90-260): three allocation
modes —

* **static** (``staticAllocation``): the node->prefix map comes from
  config and/or the ``e2e-network-allocations`` KvStore key, updated
  live;
* **dynamic root** (``dynamicAllocationRootNode``): seed prefix + alloc
  length come from config, a unique sub-prefix index is elected via
  RangeAllocator consensus over the KvStore;
* **dynamic leaf** (``dynamicAllocationLeafNode``): allocation params
  are learned from the ``e2e-network-prefix`` KvStore key (value
  ``"<seed-prefix>,<alloc-len>"``) and re-elections follow param
  changes.

The elected prefix is advertised through the PrefixManager, programmed
on the loopback via netlink (old addresses are removed on change —
reference applyMyPrefix/withdrawMyPrefix), and the elected index is
persisted so restarts re-claim the same sub-prefix
(reference loadPrefixIndexFromDisk/savePrefixIndexToDisk).
"""

from __future__ import annotations

import json
from typing import Callable, Dict, Optional, Tuple

from openr_tpu.monitor.monitor import push_log_sample
from openr_tpu.allocators.range_allocator import RangeAllocator
from openr_tpu.types import BinaryAddress, IpPrefix, PrefixEntry, PrefixType
from openr_tpu.utils.eventbase import OpenrEventBase

ALLOC_PREFIX_MARKER = "allocprefix:"  # reference: Constants kPrefixAllocMarker
# reference: Constants.h:112 kSeedPrefixAllocParamKey
SEED_ALLOC_PARAM_KEY = "e2e-network-prefix"
# reference: Constants.h:117 kStaticPrefixAllocParamKey
STATIC_ALLOC_KEY = "e2e-network-allocations"
PERSIST_KEY = "prefix-allocator-index"

AllocParams = Tuple[IpPrefix, int]  # (seed prefix, alloc prefix length)


def sub_prefix(seed: IpPrefix, alloc_len: int, index: int) -> IpPrefix:
    """Carve the index-th /alloc_len prefix out of the seed prefix."""
    assert alloc_len >= seed.prefix_length
    addr_bits = len(seed.prefix_address.addr) * 8
    base = int.from_bytes(seed.prefix_address.addr, "big")
    offset = index << (addr_bits - alloc_len)
    return IpPrefix(
        prefix_address=BinaryAddress(
            addr=(base | offset).to_bytes(addr_bits // 8, "big")
        ),
        prefix_length=alloc_len,
    )


def prefix_contains(outer: IpPrefix, inner: IpPrefix) -> bool:
    """True when ``inner`` lies within ``outer``'s address space."""
    if len(outer.prefix_address.addr) != len(inner.prefix_address.addr):
        return False
    if inner.prefix_length < outer.prefix_length:
        return False
    bits = outer.prefix_length
    o = int.from_bytes(outer.prefix_address.addr, "big")
    i = int.from_bytes(inner.prefix_address.addr, "big")
    shift = 8 * len(outer.prefix_address.addr) - bits
    return (o >> shift) == (i >> shift)


def parse_alloc_params(text: str) -> AllocParams:
    """Parse ``"fc00:cafe::/56,64"`` (reference: PrefixAllocator.cpp
    parseParamsStr)."""
    seed_str, _, len_str = text.partition(",")
    seed = IpPrefix.from_str(seed_str.strip())
    alloc_len = int(len_str.strip())
    if alloc_len < seed.prefix_length:
        raise ValueError(
            f"alloc length /{alloc_len} shorter than seed "
            f"/{seed.prefix_length}"
        )
    return seed, alloc_len


class PrefixAllocator:
    def __init__(
        self,
        my_node_name: str,
        evb: OpenrEventBase,
        kvstore_client,
        prefix_manager,
        seed_prefix: Optional[IpPrefix] = None,
        alloc_prefix_len: int = 64,
        static_prefixes: Optional[Dict[str, IpPrefix]] = None,
        netlink=None,
        loopback_if: str = "lo",
        config_store=None,
        area: str = "0",
        on_allocated: Optional[Callable[[Optional[IpPrefix]], None]] = None,
        log_sample_queue=None,
    ):
        self._node = my_node_name
        self._evb = evb
        self._client = kvstore_client
        self._prefix_manager = prefix_manager
        self._log_sample_queue = log_sample_queue
        self._netlink = netlink
        self._loopback_if = loopback_if
        self._config_store = config_store
        self._area = area
        self._on_allocated = on_allocated
        self.allocated_prefix: Optional[IpPrefix] = None
        self._programmed_prefix: Optional[IpPrefix] = None
        # every seed this allocator has worked under: the loopback sync
        # treats addresses inside these spaces as ours to clean up
        self._known_seeds: set = set()
        self._alloc_params: Optional[AllocParams] = None
        self._range_allocator: Optional[RangeAllocator] = None
        self._alloc_token: Optional[object] = None
        self._static_mode = static_prefixes is not None
        self._stopped = False

        if self._static_mode:
            # static mode: allocation from config, live-updatable via the
            # e2e-network-allocations key (reference: staticAllocation)
            prefix = static_prefixes.get(my_node_name)
            if prefix is not None:
                self._evb.run_in_event_base(lambda: self._apply(prefix))
            if self._client is not None:
                self._client.subscribe_key(
                    area, STATIC_ALLOC_KEY, self._on_static_alloc_update
                )
            return

        if seed_prefix is not None:
            # dynamic root: params from config
            self.update_alloc_params(seed_prefix, alloc_prefix_len)
            return

        # dynamic leaf: params learned from the KvStore
        # (reference: dynamicAllocationLeafNode)
        assert self._client is not None, "leaf mode needs a KvStore client"
        self._client.subscribe_key(
            area, SEED_ALLOC_PARAM_KEY, self._on_alloc_param_update
        )
        existing = self._client.get_key(area, SEED_ALLOC_PARAM_KEY)
        if existing is not None and existing.value is not None:
            self._on_alloc_param_update(SEED_ALLOC_PARAM_KEY, existing)

    def stop(self) -> None:
        self._stopped = True
        self._alloc_token = None
        if self._range_allocator is not None:
            self._range_allocator.stop()

    # -- public -----------------------------------------------------------

    def get_alloc_params(self) -> Optional[AllocParams]:
        return self._alloc_params

    def update_alloc_params(
        self,
        seed_prefix: Optional[IpPrefix],
        alloc_prefix_len: int = 64,
    ) -> None:
        """(Re)start allocation from new params; ``None`` seed withdraws
        the current allocation. reference: PrefixAllocator.cpp
        startAllocation — 'can be called again with new prefix or
        std::nullopt'."""
        new_params = (
            None
            if seed_prefix is None
            else (seed_prefix, alloc_prefix_len)
        )
        if new_params == self._alloc_params and new_params is not None:
            return
        if new_params != self._alloc_params:  # None -> None is a no-op
            self._log_prefix_event(
                "ALLOC_PARAMS_UPDATE",
                old_params=(
                    f"{self._alloc_params[0].to_str()},"
                    f"{self._alloc_params[1]}"
                    if self._alloc_params
                    else ""
                ),
                new_params=(
                    f"{seed_prefix.to_str()},{alloc_prefix_len}"
                    if seed_prefix is not None
                    else ""
                ),
            )
        if self._range_allocator is not None:
            self._range_allocator.stop()
            self._range_allocator = None
        self._alloc_token = None
        self._evb.run_immediately_or_in_event_base(self._withdraw)
        self._alloc_params = new_params
        if new_params is None:
            return

        seed, alloc_len = new_params
        self._known_seeds.add(seed)
        count = 1 << (alloc_len - seed.prefix_length)
        init_index = None
        if self._config_store is not None:
            persisted = self._config_store.load(PERSIST_KEY)
            # resume only if the persisted index was elected under the
            # SAME params (reference: loadPrefixIndexFromDisk)
            if (
                isinstance(persisted, (list, tuple))
                and len(persisted) == 3
                and persisted[0] == seed.to_str()
                and persisted[1] == alloc_len
                and 0 <= persisted[2] < count
            ):
                init_index = persisted[2]
        # bind the params generation into the callback: a claim that
        # resolves after the next update_alloc_params/stop must not
        # apply a stale index against the new seed space
        token = object()
        self._alloc_token = token
        self._range_allocator = RangeAllocator(
            self._evb,
            self._client,
            self._node,
            f"{ALLOC_PREFIX_MARKER}{seed.to_str()}/{alloc_len}:",
            (0, count - 1),
            lambda index: self._on_index(index, token, new_params),
            area=self._area,
        )
        self._range_allocator.start_allocator(init_value=init_index)

    # -- KvStore-driven updates ------------------------------------------

    def _on_alloc_param_update(self, key, value) -> None:
        """reference: PrefixAllocator.cpp processAllocParamUpdate."""
        del key
        if self._stopped or value is None or value.value is None:
            return
        try:
            seed, alloc_len = parse_alloc_params(
                value.value.decode("utf-8")
            )
        except (ValueError, UnicodeDecodeError):
            return  # malformed params: keep the current allocation
        self.update_alloc_params(seed, alloc_len)

    def _on_static_alloc_update(self, key, value) -> None:
        """reference: PrefixAllocator.cpp processStaticPrefixAllocUpdate.
        Value: JSON ``{node_name: "prefix/len", ...}``."""
        del key
        if self._stopped or value is None or value.value is None:
            return
        try:
            allocations = json.loads(value.value.decode("utf-8"))
            mine = allocations.get(self._node)
        except (ValueError, UnicodeDecodeError, AttributeError):
            return
        if mine is None:
            self._evb.run_immediately_or_in_event_base(self._withdraw)
            return
        try:
            prefix = IpPrefix.from_str(mine)
        except ValueError:
            return
        self._evb.run_immediately_or_in_event_base(
            lambda: self._apply(prefix)
        )

    # -- internals --------------------------------------------------------

    def _log_prefix_event(self, event: str, **fields) -> None:
        """reference: PrefixAllocator.cpp logPrefixEvent —
        PREFIX_ELECTED / PREFIX_UPDATED / PREFIX_LOST /
        ALLOC_PARAMS_UPDATE samples toward the Monitor."""
        push_log_sample(
            self._log_sample_queue,
            node_name=self._node,
            event=event,
            **fields,
        )

    def _on_index(
        self,
        index: Optional[int],
        token: object,
        params: AllocParams,
    ) -> None:
        if token is not self._alloc_token:
            return  # stale allocator generation
        if index is None:
            self._withdraw()
            return
        seed, alloc_len = params
        if self._config_store is not None:
            self._config_store.store(
                PERSIST_KEY, [seed.to_str(), alloc_len, index]
            )
        self._apply(sub_prefix(seed, alloc_len, index))

    def _apply(self, prefix: IpPrefix) -> None:
        if prefix == self.allocated_prefix:
            return
        old = self.allocated_prefix
        self._log_prefix_event(
            "PREFIX_UPDATED" if old else "PREFIX_ELECTED",
            prefix=prefix.to_str(),
            old_prefix=old.to_str() if old else "",
        )
        # the loopback sweep happens once, in the sync below — not in
        # the intermediate withdraw too; the UPDATED sample above covers
        # the old prefix, so the withdraw does not log a separate LOST
        self._withdraw(sync_loopback=False, log=False)
        self.allocated_prefix = prefix
        self._prefix_manager.advertise_prefixes(
            [
                PrefixEntry(
                    prefix=prefix, type=PrefixType.PREFIX_ALLOCATOR
                )
            ]
        )
        self._sync_loopback_address(prefix)
        if self._on_allocated is not None:
            self._on_allocated(prefix)

    def _withdraw(
        self, sync_loopback: bool = True, log: bool = True
    ) -> None:
        had = self.allocated_prefix is not None
        if had:
            if log:
                self._log_prefix_event(
                    "PREFIX_LOST", prefix=self.allocated_prefix.to_str()
                )
            self._prefix_manager.withdraw_prefixes([self.allocated_prefix])
            self.allocated_prefix = None
        if sync_loopback:
            self._sync_loopback_address(None)
        if had and self._on_allocated is not None:
            self._on_allocated(None)

    def _sync_loopback_address(
        self, prefix: Optional[IpPrefix]
    ) -> None:
        """Program the new prefix on the loopback and remove stale ones
        (reference: PrefixAllocator.cpp:780 syncIfaceAddrs — add the
        desired set, delete everything else in scope). "In scope" here
        means: the previously programmed address, plus any kernel
        address that lies inside a seed prefix this allocator has been
        configured with — so a restarted daemon cleans up a prior
        incarnation's allocation without ever touching unrelated
        addresses (::1, operator-configured loopbacks)."""
        if self._netlink is None or prefix == self._programmed_prefix:
            return
        stale = set()
        if self._programmed_prefix is not None:
            stale.add(self._programmed_prefix)
        try:
            existing = self._netlink.get_ifaddresses(self._loopback_if)
        except Exception:
            existing = []
        for addr in existing:
            for seed in self._known_seeds:
                if prefix_contains(seed, addr) and addr != prefix:
                    stale.add(addr)
                    break
        for addr in stale:
            if addr == prefix:
                continue
            try:
                self._netlink.del_ifaddress(self._loopback_if, addr)
            except Exception:
                pass
        self._programmed_prefix = None
        if prefix is not None:
            if prefix in existing:
                # already programmed (restart re-claiming the same
                # index): adopt it — the Linux add would EEXIST
                self._programmed_prefix = prefix
                return
            try:
                self._netlink.add_ifaddress(self._loopback_if, prefix)
                self._programmed_prefix = prefix
            except Exception:
                pass
