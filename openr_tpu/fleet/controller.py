"""FleetController: placement, live migration, hot-standby failover.

One controller runs N *managed services*. Each ``ManagedService`` is a
primary ``SolverService`` behind its own ctrl port plus (by default) a
hot standby: a second service+handler pair fed the primary's adopted-
publication journal by a ``JournalStreamer`` over the ctrl wire — the
standby applies, solves, and holds route products, so it is HOT, not
a cold spare.

Three fleet transitions, all inside the existing degradation
machinery (never silent):

- **admit** — weighted-occupancy placement by SLO class
  (fleet/placement.py), counted ``fleet.placements``; the client asks
  the controller (``fleet_admit`` / ``fleet_lookup``) which endpoint
  owns its tenant.
- **migrate** — drain on A (freeze + quiesce), ship host snapshot +
  un-replayed journal tail over the ctrl wire, rehydrate warm on B,
  seal (redirect installed on A), counted ``fleet.migrations`` with a
  ``fleet.migration_ms`` histogram. A failed import aborts back to A
  (tenant parked warm, ``fleet.migration_aborts``) — bits never at
  risk, only the move.
- **promote** — on ``device.lost`` or primary death the standby takes
  over under graceful-restart semantics: ONE reconcile, zero route
  deletes. The walk is a two-rung ``DegradationSupervisor`` ladder:
  rung 0 flushes the journal suffix to the standby first (the
  never-promote-past-an-un-shipped-suffix rule, satisfied by making
  the suffix empty); the fallback rung promotes at the standby's
  applied seq and SURRENDERS the un-shipped suffix counted
  (``fleet.promotion_unshipped``) — the crash case, degraded loudly
  within the ladder, never silently. The ``fleet.promote`` fault seam
  sits at the head of rung 0 so the chaos leg can force the walk down
  the ladder.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from openr_tpu.analysis.annotations import runs_on
from openr_tpu.ctrl.server import CtrlClient, CtrlServer
from openr_tpu.ctrl.solver import SolverCtrlHandler
from openr_tpu.faults import fault_point, register_fault_site
from openr_tpu.faults.supervisor import DegradationSupervisor
from openr_tpu.fleet.journal import FleetJournal, JournalStreamer
from openr_tpu.fleet.placement import (
    FLEET_COUNTERS,
    PlacementPolicy,
    ServiceLoad,
    placement_table,
)
from openr_tpu.serve.service import SolverService
from openr_tpu.telemetry import (
    get_flight_recorder,
    get_registry as _get_registry,
)

FAULT_PROMOTE = register_fault_site("fleet.promote")


class ManagedService:
    """One fleet slot: primary service + ctrl server, hot standby +
    ctrl server, and the journal stream tying them together. ``port``
    always names the endpoint clients should dial — promotion swaps
    it to the standby's."""

    def __init__(self, name: str, host: str = "127.0.0.1",
                 with_standby: bool = True,
                 stream_interval_s: float = 0.02,
                 wave_budget: Optional[int] = None):
        self.name = name
        self.host = host
        self.journal = FleetJournal()
        self.service = SolverService(wave_budget=wave_budget)
        self.handler = SolverCtrlHandler(
            self.service, journal=self.journal, role="primary"
        )
        self.server = CtrlServer(self.handler, host=host, port=0)
        self.port = self.server.port
        self.standby_service: Optional[SolverService] = None
        self.standby_handler: Optional[SolverCtrlHandler] = None
        self.standby_server: Optional[CtrlServer] = None
        self.standby_port: Optional[int] = None
        self.streamer: Optional[JournalStreamer] = None
        self._stream_cli: Optional[CtrlClient] = None
        self.promoted = False
        if with_standby:
            self.standby_service = SolverService(
                wave_budget=wave_budget
            )
            self.standby_handler = SolverCtrlHandler(
                self.standby_service, journal=None, role="standby"
            )
            self.standby_server = CtrlServer(
                self.standby_handler, host=host, port=0
            )
            self.standby_port = self.standby_server.port
            self.streamer = JournalStreamer(
                self.journal, self._ship,
                interval_s=stream_interval_s,
                name=f"fleet-streamer-{name}",
            )

    # -- lifecycle ---------------------------------------------------

    def start(self) -> "ManagedService":
        self.service.start()
        self.server.start()
        if self.standby_service is not None:
            self.standby_service.start()
            self.standby_server.start()
            self.streamer.start()
        return self

    def stop(self) -> None:
        if self.streamer is not None:
            self.streamer.stop()
        self._close_stream_cli()
        for server in (self.server, self.standby_server):
            if server is not None:
                try:
                    server.stop()
                except OSError:
                    pass
        for svc in (self.service, self.standby_service):
            if svc is not None:
                svc.stop()

    # -- journal stream (runs on the streamer thread only) -----------

    def _ship(self, frames: List[Dict]) -> int:
        if self.standby_port is None:
            raise ConnectionError("no standby to ship to")
        try:
            if self._stream_cli is None:
                self._stream_cli = CtrlClient(
                    self.host, self.standby_port
                )
            reply = self._stream_cli.call(
                "solver_replica_apply", records=frames
            )
            return int(reply["applied_seq"])
        except Exception:
            # drop the wire so the retry re-dials fresh
            self._close_stream_cli()
            raise

    def _close_stream_cli(self) -> None:
        if self._stream_cli is not None:
            try:
                self._stream_cli.close()
            except OSError:
                pass
            self._stream_cli = None

    # -- failure / takeover ------------------------------------------

    def alive(self) -> bool:
        """Is the PRIMARY answering its wire?"""
        try:
            cli = CtrlClient(self.host, self.port)
            try:
                cli.call("solver_ping")
            finally:
                cli.close()
            return True
        except (ConnectionError, OSError, RuntimeError):
            return False

    def kill_primary(self) -> None:
        """Abrupt primary death (tests/chaos): the wire drops with no
        handover — exactly what ``maybe_failover`` must detect."""
        try:
            self.server.stop()
        except OSError:
            pass
        self.service.stop()

    def adopt_standby(self) -> None:
        """Post-promotion bookkeeping: the standby IS the service now.
        The old primary (dead or being retired) is stopped; the
        advertised endpoint flips; the stream ends (the new primary
        runs without a standby until the operator re-seeds one)."""
        if self.standby_server is None:
            raise RuntimeError(f"{self.name}: no standby to adopt")
        if self.streamer is not None:
            self.streamer.stop()
            self.streamer = None
        self._close_stream_cli()
        try:
            self.server.stop()
        except OSError:
            pass
        self.service.stop()
        self.service = self.standby_service
        self.handler = self.standby_handler
        self.server = self.standby_server
        self.port = self.standby_port
        self.standby_service = None
        self.standby_handler = None
        self.standby_server = None
        self.standby_port = None
        self.promoted = True


class FleetController:
    """Owns the placement table and drives every fleet transition.
    Thread model: public methods run on whatever thread calls them
    (tests, tools, the controller's own ctrl handler threads) —
    ``_lock`` guards the placement maps; each wire conversation uses
    its own short-lived ``CtrlClient``."""

    def __init__(self, services: int = 2, with_standby: bool = True,
                 host: str = "127.0.0.1", capacity: int = 64,
                 wave_budget: Optional[int] = None,
                 stream_interval_s: float = 0.02):
        self._lock = threading.RLock()
        self._policy = PlacementPolicy()
        self._services: Dict[str, ManagedService] = {}
        self._loads: Dict[str, ServiceLoad] = {}
        for i in range(services):
            name = f"svc{i}"
            self._services[name] = ManagedService(
                name, host=host, with_standby=with_standby,
                stream_interval_s=stream_interval_s,
                wave_budget=wave_budget,
            )
            self._loads[name] = ServiceLoad(name, capacity=capacity)
        self._ctrl: Optional[CtrlServer] = None
        self._promote_sup = DegradationSupervisor(
            "fleet.promote_ladder",
            backoff_min_s=0.01, backoff_max_s=0.2,
        )
        self._reg = _get_registry()

    # -- lifecycle ---------------------------------------------------

    def start(self) -> "FleetController":
        for ms in self._services.values():
            ms.start()
        return self

    def stop(self) -> None:
        if self._ctrl is not None:
            try:
                self._ctrl.stop()
            except OSError:
                pass
            self._ctrl = None
        for ms in self._services.values():
            ms.stop()

    def serve_ctrl(self, host: str = "127.0.0.1") -> int:
        """Put the controller itself on the ctrl wire (fleet_lookup /
        fleet_admit / fleet_services) — the endpoint redirect-chasing
        clients fall back to. Returns the bound port."""
        self._ctrl = CtrlServer(
            FleetCtrlHandler(self), host=host, port=0
        )
        self._ctrl.start()
        return self._ctrl.port

    # -- placement ---------------------------------------------------

    def services(self) -> Dict[str, ManagedService]:
        return dict(self._services)

    def placement(self) -> Dict[str, Dict]:
        with self._lock:
            table = placement_table(self._loads.values())
        for name, row in table.items():
            ms = self._services[name]
            row["endpoint"] = [ms.host, ms.port]
            row["standby"] = (
                [ms.host, ms.standby_port]
                if ms.standby_port is not None else None
            )
            row["promoted"] = ms.promoted
        return table

    def admit(self, tenant_id: str,
              slo: str = "standard") -> Tuple[str, int]:
        """Place the tenant; returns the endpoint it should register
        with. Placement is a table entry — the client still does its
        own ``solver_register`` against the endpoint."""
        with self._lock:
            row = self._policy.place(
                sorted(self._loads.values(), key=lambda s: s.name),
                tenant_id, slo,
            )
            ms = self._services[row.name]
            return (ms.host, ms.port)

    def owner_of(self, tenant_id: str) -> str:
        with self._lock:
            for name, row in self._loads.items():
                if tenant_id in row.tenants:
                    return name
        raise KeyError(f"tenant {tenant_id!r} not placed")

    def lookup(self, tenant_id: str) -> Dict[str, object]:
        """Current endpoint for a tenant — survives migrations AND
        promotions (the managed service's advertised port flips with
        the takeover)."""
        name = self.owner_of(tenant_id)
        ms = self._services[name]
        return {"service": name, "host": ms.host, "port": ms.port}

    # -- live migration ----------------------------------------------

    def migrate(self, tenant_id: str,
                dst: Optional[str] = None) -> Dict[str, object]:
        """Drain on A, ship, rehydrate warm on B, seal. Returns the
        import reply (``warm`` is the no-cold-solve witness)."""
        with self._lock:
            src_name = self.owner_of(tenant_id)
            slo = self._loads[src_name].tenants[tenant_id]
            if dst is None:
                dst = self._policy.choose(
                    sorted(self._loads.values(),
                           key=lambda s: s.name),
                    slo, exclude=[src_name],
                ).name
            if dst == src_name:
                raise ValueError(
                    f"migrate {tenant_id!r}: dst == src ({dst})"
                )
            src_ms = self._services[src_name]
            dst_ms = self._services[dst]
        t0 = time.perf_counter()
        src_cli = CtrlClient(src_ms.host, src_ms.port)
        try:
            bundle = src_cli.call(
                "solver_export", tenant_id=tenant_id
            )
            try:
                dst_cli = CtrlClient(dst_ms.host, dst_ms.port)
                try:
                    reply = dst_cli.call(
                        "solver_import", bundle=bundle
                    )
                finally:
                    dst_cli.close()
            except Exception:
                # import failed: thaw on A, tenant parked warm there
                src_cli.call(
                    "solver_abort_migration", tenant_id=tenant_id
                )
                FLEET_COUNTERS["migration_aborts"] += 1
                raise
            src_cli.call(
                "solver_seal_migration", tenant_id=tenant_id,
                host=dst_ms.host, port=dst_ms.port,
            )
        finally:
            src_cli.close()
        with self._lock:
            self._loads[src_name].evict(tenant_id)
            self._loads[dst].admit(tenant_id, slo)
        ms_elapsed = (time.perf_counter() - t0) * 1000.0
        FLEET_COUNTERS["migrations"] += 1
        self._reg.observe("fleet.migration_ms", ms_elapsed)
        get_flight_recorder().note(
            "fleet.migrate",
            tenant=tenant_id, src=src_name, dst=dst,
            warm=bool(reply.get("warm")),
            ms=round(ms_elapsed, 3),
        )
        return dict(reply, src=src_name, dst=dst)

    # -- failover ----------------------------------------------------

    def promote(self, name: str,
                reason: str = "operator") -> Dict[str, object]:
        """Standby takeover for one service, walked down the ladder
        (see module docstring). Raises ``LadderExhausted`` if even the
        at-applied-seq rung cannot complete."""
        ms = self._services[name]
        if ms.standby_port is None:
            raise RuntimeError(f"{name}: no standby to promote")

        def _promote_at(surrendered: int) -> Dict[str, object]:
            cli = CtrlClient(ms.host, ms.standby_port)
            try:
                summary = cli.call("solver_promote")
            finally:
                cli.close()
            deletes = int(summary.get("deletes", 0))
            FLEET_COUNTERS["promotions"] += 1
            FLEET_COUNTERS["promotion_deletes"] += deletes
            if surrendered:
                FLEET_COUNTERS["promotion_unshipped"] += surrendered
            ms.adopt_standby()
            get_flight_recorder().note(
                "fleet.promote",
                service=name, reason=reason, deletes=deletes,
                surrendered=surrendered,
                applied_seq=summary.get("applied_seq"),
            )
            return dict(
                summary, service=name, surrendered=surrendered
            )

        def rung_flush_and_promote() -> Dict[str, object]:
            # the chaos seam: an armed schedule fails this rung so
            # the walk degrades (counted by the supervisor) instead
            # of taking the clean path
            fault_point(FAULT_PROMOTE)
            if ms.streamer is None or not ms.streamer.flush(
                timeout_s=5.0
            ):
                raise RuntimeError(
                    f"{name}: journal suffix not shipped"
                )
            return _promote_at(surrendered=0)

        def rung_promote_at_applied_seq() -> Dict[str, object]:
            # crash rung: the primary (or its wire) is gone — promote
            # at the standby's applied seq, surrendering the
            # un-shipped suffix COUNTED, never silently
            unshipped = (
                len(ms.streamer.unshipped())
                if ms.streamer is not None else 0
            )
            return _promote_at(surrendered=unshipped)

        return self._promote_sup.run([
            ("flush_and_promote", rung_flush_and_promote),
            ("promote_at_applied_seq", rung_promote_at_applied_seq),
        ])

    def fail_over(self, name: str,
                  reason: str = "device.lost") -> Dict[str, object]:
        """Deliberate failover (injected ``device.lost``, operator
        drain): same ladder as ``promote`` — the flush rung still
        applies because the primary HOST may be healthy even when its
        device is lost."""
        return self.promote(name, reason=reason)

    def maybe_failover(self) -> List[str]:
        """Detection sweep: ping every primary; promote the dead ones.
        Returns the promoted service names."""
        promoted: List[str] = []
        for name, ms in list(self._services.items()):
            if ms.promoted or ms.standby_port is None:
                continue
            if ms.alive():
                continue
            FLEET_COUNTERS["failovers_detected"] += 1
            self.promote(name, reason="primary_death")
            promoted.append(name)
        return promoted

    # -- introspection -----------------------------------------------

    def counters(self) -> Dict[str, float]:
        snap = self._reg.snapshot()
        return {
            k: v for k, v in snap.items() if k.startswith("fleet.")
        }


@runs_on("ctrl")
class FleetCtrlHandler:
    """The controller's own wire surface: what a redirect-chasing
    client (serve/client.py) falls back to when its cached endpoint
    stops answering. Every served lookup is a redirect, counted."""

    def __init__(self, controller: FleetController):
        self._fc = controller

    def fleet_lookup(self, tenant_id: str) -> Dict[str, object]:
        endpoint = self._fc.lookup(tenant_id)
        FLEET_COUNTERS["client_redirects"] += 1
        return endpoint

    def fleet_admit(self, tenant_id: str,
                    slo: str = "standard") -> Dict[str, object]:
        host, port = self._fc.admit(tenant_id, slo)
        return {"host": host, "port": port}

    def fleet_services(self) -> Dict[str, Dict]:
        return self._fc.placement()

    def fleet_counters(self) -> Dict[str, float]:
        return self._fc.counters()
