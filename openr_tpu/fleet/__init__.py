"""Fleet plane: placement, live tenant migration, hot-standby
failover (ISSUE 20).

- ``placement`` — weighted-occupancy SLO-class placement (jax-free)
  and the ``FLEET_COUNTERS`` table.
- ``journal`` — the adopted-publication ``FleetJournal`` + the
  ``JournalStreamer`` that feeds each service's hot standby
  (``fleet.journal_stream`` fault seam, ``fleet.replica_lag`` gauge).
- ``controller`` — ``ManagedService`` (primary + standby + stream)
  and ``FleetController`` (admit / migrate / promote / fail_over),
  with the ``fleet.promote`` seam on the takeover ladder.
"""

from openr_tpu.fleet.journal import (
    FAULT_JOURNAL_STREAM,
    FleetJournal,
    FleetRecord,
    JournalStreamer,
)
from openr_tpu.fleet.placement import (
    FLEET_COUNTERS,
    FleetAdmissionError,
    PlacementPolicy,
    ServiceLoad,
    SLO_WEIGHT,
    placement_table,
)

# The controller pulls in the whole serve/ctrl stack, and ctrl/solver
# itself imports fleet.journal — eager re-export here would close an
# import cycle. PEP 562 lazy attribute access breaks it: the
# controller module only loads when someone asks for it.
_CONTROLLER_EXPORTS = (
    "FAULT_PROMOTE",
    "FleetController",
    "FleetCtrlHandler",
    "ManagedService",
)


def __getattr__(name):
    if name in _CONTROLLER_EXPORTS:
        from openr_tpu.fleet import controller

        return getattr(controller, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


__all__ = [
    "FAULT_JOURNAL_STREAM",
    "FAULT_PROMOTE",
    "FLEET_COUNTERS",
    "FleetAdmissionError",
    "FleetController",
    "FleetCtrlHandler",
    "FleetJournal",
    "FleetRecord",
    "JournalStreamer",
    "ManagedService",
    "PlacementPolicy",
    "SLO_WEIGHT",
    "ServiceLoad",
    "placement_table",
]
