"""Adopted-publication journal stream: the hot-standby's feed.

DeltaPath's framing (PAPERS.md) made the adopted-publication journal
the system of record; the fleet plane leans on that. Every mutation a
primary service adopts for a tenant — register, world update, detach —
is appended here as a ``FleetRecord``; a ``JournalStreamer`` thread
ships the un-shipped suffix to the service's standby over the ctrl
wire and tracks how far the standby has APPLIED (`fleet.replica_lag`,
bounded by the stream cadence).

The hazard rule this module exists to make enforceable: **never
promote a standby past an un-shipped journal suffix.** The suffix is
computed by the same ``state.plane.journal_suffix`` fold recovery
uses; a planned promotion flushes it to empty first, and a crash
promotion (primary unreachable, nothing left to flush) surrenders it
*counted* (``fleet.promotion_unshipped``), never silently.

The ``fleet.journal_stream`` fault seam sits on the ship path: an
armed schedule makes a ship attempt fail exactly like a wire fault —
the suffix stays queued, the lag gauge grows, the error is counted,
and the streamer retries under its jittered backoff. Nothing is
dropped and nothing is silent, which is what the chaos fleet leg
verifies.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from openr_tpu.analysis.annotations import guarded_by, runs_on
from openr_tpu.faults import (
    FaultInjected,
    fault_point,
    register_fault_site,
)
from openr_tpu.fleet.placement import FLEET_COUNTERS
from openr_tpu.state.plane import journal_suffix
from openr_tpu.telemetry import get_registry as _get_registry
from openr_tpu.utils.eventbase import ExponentialBackoff

FAULT_JOURNAL_STREAM = register_fault_site("fleet.journal_stream")


class FleetRecord:
    """One adopted tenant mutation, in ship order. ``payload`` is
    jsonable (world blobs ride as b64 strings, same as the client
    wire) so a record crosses the ctrl transport unmodified."""

    __slots__ = ("seq", "kind", "tenant_id", "payload")

    KINDS = ("register", "update", "detach")

    def __init__(self, seq: int, kind: str, tenant_id: str,
                 payload: Dict[str, object]):
        if kind not in self.KINDS:
            raise ValueError(f"unknown journal record kind: {kind!r}")
        self.seq = seq
        self.kind = kind
        self.tenant_id = tenant_id
        self.payload = payload

    def to_wire(self) -> Dict[str, object]:
        return {
            "seq": self.seq,
            "kind": self.kind,
            "tenant_id": self.tenant_id,
            "payload": self.payload,
        }

    @staticmethod
    def from_wire(frame: Dict[str, object]) -> "FleetRecord":
        return FleetRecord(
            int(frame["seq"]), str(frame["kind"]),
            str(frame["tenant_id"]), dict(frame["payload"]),
        )


@guarded_by("FleetJournal._lock", "_records", "_next_seq")
class FleetJournal:
    """Append-only, totally ordered, bounded. The bound is a safety
    valve against a standby that is down for good — when the tail
    outgrows ``cap`` the oldest records are truncated (counted
    ``fleet.journal_truncations``) and a standby behind the truncation
    horizon must resync via a full snapshot, exactly like a KvStore
    peer behind the checkpoint."""

    def __init__(self, cap: int = 8192):
        self._lock = threading.Lock()
        self._records: List[FleetRecord] = []
        self._next_seq = 1
        self._cap = max(16, cap)
        self._reg = _get_registry()

    def append(self, kind: str, tenant_id: str,
               payload: Dict[str, object]) -> int:
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._records.append(
                FleetRecord(seq, kind, tenant_id, payload)
            )
            if len(self._records) > self._cap:
                drop = len(self._records) - self._cap
                del self._records[:drop]
                self._reg.counter_bump(
                    "fleet.journal_truncations", drop
                )
        FLEET_COUNTERS["journal_records"] += 1
        return seq

    def suffix(self, applied_seq: int) -> List[FleetRecord]:
        """The un-applied tail past ``applied_seq`` — the recovery
        fold's suffix rule applied to the replica stream."""
        with self._lock:
            return journal_suffix(self._records, applied_seq)

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._next_seq - 1

    @property
    def horizon_seq(self) -> int:
        """Oldest retained seq (a standby applied below this must
        snapshot-resync)."""
        with self._lock:
            return self._records[0].seq if self._records else self._next_seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


@runs_on("fleet-streamer")
class JournalStreamer:
    """Ships a primary's journal suffix to its standby.

    ``ship`` is injected by the controller: it takes a list of wire
    records and returns the standby's new APPLIED seq (the standby
    applies in order and answers with how far it got — idempotent on
    replayed prefixes, so a retry after a half-failed ship is safe).
    One thread per (primary, standby) pair; wire faults and the
    ``fleet.journal_stream`` seam both land on the same counted-and-
    retried path."""

    def __init__(
        self,
        journal: FleetJournal,
        ship: Callable[[List[Dict]], int],
        interval_s: float = 0.02,
        backoff_min_s: float = 0.02,
        backoff_max_s: float = 0.5,
        name: str = "fleet-streamer",
    ):
        self._journal = journal
        self._ship = ship
        self._interval_s = interval_s
        self._backoff = ExponentialBackoff(
            backoff_min_s, backoff_max_s, jitter=True, seed=0xF1EE7
        )
        self._wake = threading.Event()
        self._stop = False
        self._shipped_seq = 0
        self._lag_name = f"fleet.replica_lag.{name}"
        self._reg = _get_registry()
        # the literal thread name doubles as the thread's role label for
        # the shared-state rule — it must match this class's @runs_on
        # role so the stream loop and the control methods (stop/flush,
        # also pinned to that role) are one role, not two
        self._thread = threading.Thread(
            target=self._run, name="fleet-streamer", daemon=True
        )

    # -- lifecycle ---------------------------------------------------

    def start(self) -> "JournalStreamer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)

    # -- introspection ----------------------------------------------

    @property
    def shipped_seq(self) -> int:
        return self._shipped_seq

    def lag(self) -> int:
        """Journal records the standby has not applied yet — the
        replica-lag gauge's value, bounded by the stream cadence when
        the wire is healthy."""
        return max(0, self._journal.last_seq - self._shipped_seq)

    def unshipped(self) -> List[FleetRecord]:
        """The hazard suffix: records a promotion-at-applied-seq would
        surrender. Empty is the planned-promotion precondition."""
        return self._journal.suffix(self._shipped_seq)

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until the suffix is empty (True) or the deadline
        passes (False). The planned-promotion barrier."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        self._wake.set()
        while self.lag() > 0:
            if _time.monotonic() >= deadline:
                return False
            _time.sleep(0.005)
        return True

    # -- stream loop -------------------------------------------------

    def _run(self) -> None:
        while not self._stop:
            suffix = self._journal.suffix(self._shipped_seq)
            if not suffix:
                self._publish_lag()
                self._wake.wait(self._interval_s)
                self._wake.clear()
                continue
            if not self._backoff.can_try_now():
                self._wake.wait(
                    max(
                        0.001,
                        self._backoff
                        .get_time_remaining_until_retry(),
                    )
                )
                self._wake.clear()
                continue
            try:
                # the chaos seam: an armed schedule fails this ship
                # attempt exactly like a dropped wire — counted,
                # retried under backoff, suffix intact
                fault_point(FAULT_JOURNAL_STREAM)
                applied = int(
                    self._ship([r.to_wire() for r in suffix])
                )
            except (FaultInjected, ConnectionError, OSError,
                    RuntimeError):
                FLEET_COUNTERS["journal_stream_errors"] += 1
                self._backoff.report_error()
                self._publish_lag()
                continue
            self._backoff.report_success()
            self._shipped_seq = max(self._shipped_seq, applied)
            self._publish_lag()

    def _publish_lag(self) -> None:
        lag = self.lag()
        # per-pair gauge plus the fleet-wide one the runbook watches
        # (last writer wins; each streamer publishes every loop tick,
        # so a stuck pair's lag is never masked for long)
        self._reg.counter_set(self._lag_name, lag)
        self._reg.counter_set("fleet.replica_lag", lag)
