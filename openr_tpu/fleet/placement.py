"""Fleet placement: which service owns which tenant.

Pure and jax-free — the controller composes these with live services;
the tests exercise them with nothing but dicts. Placement is weighted
occupancy: every admitted tenant costs its SLO class's weight
(premium 4 / standard 2 / bulk 1 — the same priority order the wave
scheduler uses, serve/slo.py), and a new tenant lands on the service
carrying the least weight of its OWN class first, total weight second
(ties break on service name, so placement is deterministic for the
parity gates). Balancing within the class before balancing the totals
is what keeps two premium tenants off one service while bulk piles up
on the other — occupancy AND SLO class, per the fleet contract.

This module also hosts ``FLEET_COUNTERS``, the fleet plane's counter
table (a registry-backed dict shim like ``TENANCY_COUNTERS``), because
it is the one fleet module everything else may import without cycles.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from openr_tpu.telemetry import get_registry as _get_registry

# weighted occupancy cost per SLO class (mirrors serve/slo.py priority
# order: heavier classes claim more of a service's budget)
SLO_WEIGHT: Dict[str, int] = {"premium": 4, "standard": 2, "bulk": 1}

FLEET_COUNTERS = _get_registry().counter_dict(
    [
        "placements",          # tenants admitted through the policy
        "migrations",          # sealed live migrations (A -> B warm)
        "migration_aborts",    # import failed; tenant stayed on A
        "promotions",          # standby promoted to primary
        "promotion_deletes",   # route deletes across ALL promotions (gate: 0)
        "promotion_unshipped", # journal records surrendered by a crash
        #                        promotion (rung 2) — the hazard rule's
        #                        conscious-loss counter, never silent
        "failovers_detected",  # dead primaries found by maybe_failover
        "client_redirects",    # moved_to redirects served to clients
        "journal_stream_errors",  # standby ship attempts that failed
        "journal_records",     # records appended across all journals
    ],
    prefix="fleet.",
)


class FleetAdmissionError(RuntimeError):
    """No service can take the tenant (every candidate at capacity)."""


class ServiceLoad:
    """One service's placement-table row: its admitted tenants by SLO
    class, a tenant-count capacity, and the weighted occupancy the
    policy ranks on."""

    __slots__ = ("name", "capacity", "tenants")

    def __init__(self, name: str, capacity: int = 64):
        self.name = name
        self.capacity = capacity
        self.tenants: Dict[str, str] = {}  # tenant_id -> slo class

    def weight(self) -> int:
        return sum(SLO_WEIGHT.get(s, 2) for s in self.tenants.values())

    def class_count(self, slo: str) -> int:
        return sum(1 for s in self.tenants.values() if s == slo)

    def admit(self, tenant_id: str, slo: str) -> None:
        self.tenants[tenant_id] = slo

    def evict(self, tenant_id: str) -> Optional[str]:
        return self.tenants.pop(tenant_id, None)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"ServiceLoad({self.name!r}, tenants={len(self.tenants)}, "
            f"weight={self.weight()})"
        )


class PlacementPolicy:
    """Deterministic weighted-occupancy placement with per-class
    balancing (see module docstring for the ranking rule)."""

    def choose(
        self,
        services: Sequence[ServiceLoad],
        slo: str = "standard",
        exclude: Sequence[str] = (),
    ) -> ServiceLoad:
        if slo not in SLO_WEIGHT:
            raise ValueError(f"unknown SLO class: {slo!r}")
        skip = set(exclude)
        candidates = [
            s for s in services
            if s.name not in skip and len(s.tenants) < s.capacity
        ]
        if not candidates:
            raise FleetAdmissionError(
                f"no service can admit slo={slo!r} "
                f"(fleet of {len(services)}, excluded {sorted(skip)})"
            )
        return min(
            candidates,
            key=lambda s: (s.class_count(slo), s.weight(), s.name),
        )

    def place(
        self,
        services: Sequence[ServiceLoad],
        tenant_id: str,
        slo: str = "standard",
        exclude: Sequence[str] = (),
    ) -> ServiceLoad:
        """Choose and record: the returned service already carries the
        tenant in its row. Counted ``fleet.placements``."""
        svc = self.choose(services, slo, exclude=exclude)
        svc.admit(tenant_id, slo)
        FLEET_COUNTERS["placements"] += 1
        return svc


def placement_table(services: Sequence[ServiceLoad]) -> Dict[str, Dict]:
    """The fleet's placement table, jsonable — what ``fleet_services``
    serves to breeze/ops tooling."""
    return {
        s.name: {
            "tenants": dict(sorted(s.tenants.items())),
            "weight": s.weight(),
            "capacity": s.capacity,
        }
        for s in services
    }
