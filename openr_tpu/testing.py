"""Host-side test/bench platform pinning.

Multi-chip TPU hardware is not available in CI; sharding tests and
degraded bench runs use virtual CPU devices (the standard JAX trick for
exercising pjit/shard_map topologies host-side). The ambient site hook on
relay-backed hosts pins JAX to a tunneled TPU plugin regardless of
``JAX_PLATFORMS`` — and that relay has been observed to hang indefinitely
on first touch — so the pin must both set the env knobs and force the
config value, before any backend is initialized.
"""

from __future__ import annotations

import os
import re


def pin_host_cpu(n_devices: int | None = None) -> None:
    """Force JAX onto the host CPU platform, optionally with ``n_devices``
    virtual devices.

    Idempotent and safe to call after ``import jax`` as long as no backend
    has been initialized yet. If one has, backends are cleared and
    re-initialized on the CPU platform — but XLA latches the host device
    count at first backend init, so a too-late call that cannot deliver
    ``n_devices`` raises instead of letting the caller fail confusingly
    downstream. Overwrites (not merely appends) any existing
    ``xla_force_host_platform_device_count`` flag so callers get the count
    they asked for.
    """
    # Deactivate the relay plugin BEFORE jax initializes any backend:
    # the ambient site hook registers a tunneled PJRT plugin whose
    # INITIALIZATION (not registration) dials the relay and has been
    # observed to hang indefinitely when the relay is down — even with
    # JAX_PLATFORMS=cpu, backend discovery touched it. The plugin's
    # boot code is env-driven, so dropping its knobs in this process
    # (registration already happened at interpreter start) makes the
    # deferred initialization a no-op and CPU pinning deterministic
    # regardless of relay health. TPU-path callers never call this
    # function, so the real device path is unaffected.
    for knob in (
        "PALLAS_AXON_POOL_IPS",
        "PALLAS_AXON_REMOTE_COMPILE",
        "AXON_POOL_SVC_OVERRIDE",
        "AXON_LOOPBACK_RELAY",
    ):
        os.environ.pop(knob, None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        opt = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", opt, flags
            )
        else:
            flags = (flags + " " + opt).strip()
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")

    def _ok() -> bool:
        try:
            devs = jax.devices()
            return devs[0].platform == "cpu" and (
                n_devices is None or len(devs) >= n_devices
            )
        except Exception:
            return False

    if not _ok():
        # A backend was already initialized with the wrong platform; drop
        # it so the next jax.devices() re-initializes under the pinned
        # settings. jax.extend is not auto-imported by `import jax` — the
        # explicit submodule import is required.
        try:
            import jax.extend.backend

            jax.extend.backend.clear_backends()
        except Exception:
            pass
        if not _ok():
            # XLA latches xla_force_host_platform_device_count at first
            # backend init; clearing recovers the platform but not the
            # device count, so fail loudly with the actionable cause.
            raise RuntimeError(
                "pin_host_cpu could not deliver a "
                f"{n_devices or 1}-device CPU backend: a JAX backend was "
                "already initialized in this process. Call pin_host_cpu "
                "before the first jax.devices()/device operation."
            )
