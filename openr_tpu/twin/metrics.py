"""``twin.*`` observability surface.

Counters live here (not in ``fabric``) so the analyzer and scenario
driver can bump them without importing the FabricTwin module — the
fabric imports the analyzer, never the other way around.
"""

from openr_tpu.telemetry import get_registry

TWIN_COUNTERS = get_registry().counter_dict(
    [
        "vantages",          # gauge: nodes modeled by live twins
        "events",            # publications applied to the shared LSDB
        "waves",             # fleet converge waves (one dispatch each)
        "vantage_solves",    # per-vantage route rebuilds
        "stale_vantages",    # gauge: vantages behind the shared LSDB
        "restarts",          # rolling-restart (graceful) cycles
        "partitions",        # area-partition cuts applied
        "injected_drops",    # events dropped by the twin.inject seam
        "analyses",          # fleet analyzer passes
        "loops_found",       # micro-loop findings
        "blackholes_found",  # transient-blackhole findings
    ],
    prefix="twin.",
)
