"""Scenario driver: scripted fleet-scale event sequences for the twin.

The seeded ``LoadGenerator`` supplies realistic background churn; this
driver layers the *operational* sequences on top of it — link flaps,
metric changes, drain sequencing, area partitions, rolling restarts —
using the generator's scripted seams (``emit_adjacency`` /
``emit_prefix``), which consume no RNG draws: scripted steps
interleave freely with seeded load without perturbing its schedule,
so the oracle replay of the event log stays deterministic.

Tenth fault seam: ``twin.inject``. Arming it makes injected events
drop BEFORE reaching the twin's LSDB — a lossy flood toward the whole
fleet. Dropped events are excluded from both the twin and the replay
log (the generator's full-database publication semantics mean the
next surviving event for the same key self-heals the divergence), so
twin-vs-oracle parity holds under chaos, the same contract the load
harness established.

Two injectors exist specifically to seed the analyzer's defect
classes: ``inject_micro_loop`` flaps a link and reconverges only its
endpoints (stale interior vantages still forward into the flap —
cycle), ``inject_blackhole`` advertises a fresh prefix and converges
only its originator (stale vantages lack a route to deliverable
traffic). Both heal with one full ``twin.converge()``.
"""

from __future__ import annotations

from dataclasses import replace as _dc_replace
from typing import Dict, List, Optional, Sequence, Tuple

from openr_tpu.faults.injector import (
    FaultInjected,
    fault_point,
    register_fault_site,
)
from openr_tpu.load.generator import (
    KIND_DRAIN,
    KIND_FLAP,
    KIND_METRIC,
    EventMix,
    LoadEvent,
    LoadGenerator,
    _extra_prefix,
)
from openr_tpu.twin.fabric import FabricTwin
from openr_tpu.twin.metrics import TWIN_COUNTERS
from openr_tpu.types import (
    TTL_INFINITY,
    Adjacency,
    PrefixEntry,
    Publication,
    Value,
)
from openr_tpu.utils import wire

FAULT_TWIN_INJECT = register_fault_site("twin.inject")


class ScenarioDriver:
    """Owns one twin + one seeded generator and a replay log.

    ``self.log`` holds exactly the events that reached the twin's
    LSDB (seeded and scripted alike, drops excluded) — replaying it
    through N independent Decision pipelines is the parity oracle.
    """

    def __init__(
        self,
        twin: FabricTwin,
        seed: int = 0,
        mix: Optional[EventMix] = None,
    ):
        self.twin = twin
        self.gen = LoadGenerator(twin.topo, seed=seed, mix=mix)
        # priming the version-1 bulk load keeps event versions aligned
        # with the harness convention AND gives the oracle its initial
        # publication (content-identical to the twin's topo databases)
        self.initial = self.gen.initial_key_vals()
        self.log: List[LoadEvent] = []
        # flapped/partitioned adjacencies awaiting restore:
        # (node, Adjacency) in withdrawal order
        self._withdrawn: Dict[Tuple[str, str], List[Tuple[str, Adjacency]]] = {}
        self._partition_cut: List[Tuple[str, Adjacency]] = []

    # -- event plumbing ----------------------------------------------------

    def apply(self, ev: LoadEvent) -> bool:
        """Push one event at the twin through the lossy-flood seam.
        Returns True iff the event mutated the shared LSDB."""
        if ev.dropped:
            return False
        try:
            fault_point(FAULT_TWIN_INJECT)
        except FaultInjected:
            TWIN_COUNTERS["injected_drops"] += 1
            return False
        if self.twin.apply_event(ev):
            self.log.append(ev)
            return True
        return False

    def run_load(self, n: int, converge_each: bool = True) -> List[LoadEvent]:
        """Drive ``n`` seeded background events (each one twin wave
        when ``converge_each``)."""
        out = []
        for _ in range(n):
            ev = self.gen.next_event()
            out.append(ev)
            if self.apply(ev) and converge_each:
                self.twin.converge()
        if not converge_each:
            self.twin.converge()
        return out

    # -- per-vantage oracle ------------------------------------------------

    def oracle_route_db(self, node: str):
        """The twin-vs-real parity oracle for one vantage: replay the
        surviving event log — initial bulk load plus every event that
        reached the twin — into a fresh, independently-run Decision on
        the deterministic host backend, and return its final
        DecisionRouteDb. N of these ARE the real fleet; the twin's N
        tables must match them bit for bit."""
        from openr_tpu.decision.decision import Decision
        from openr_tpu.messaging.queue import ReplicateQueue

        area = self.twin.area
        kv_q = ReplicateQueue(name=f"twin-oracle:{node}:kvstore")
        oracle = Decision(
            node,
            kvstore_updates_queue=kv_q,
            route_updates_queue=ReplicateQueue(
                name=f"twin-oracle:{node}:routes"
            ),
            solver_backend="host",
        )
        try:
            oracle.process_publication(
                Publication(key_vals=dict(self.initial), area=area)
            )
            for ev in self.log:
                oracle.process_publication(
                    Publication(
                        key_vals={
                            ev.key: Value(
                                version=ev.version,
                                originator_id=ev.node,
                                value=ev.payload,
                                ttl=TTL_INFINITY,
                                hash=wire.generate_hash(
                                    ev.version, ev.node, ev.payload
                                ),
                            )
                        },
                        area=area,
                    )
                )
            oracle.pending.set_needs_full_rebuild()
            oracle.rebuild_routes("TWIN_ORACLE")
            return oracle.route_db
        finally:
            kv_q.close()

    def check_parity(self, nodes: Optional[Sequence[str]] = None
                     ) -> List[str]:
        """Bit-compare every (or the given) vantage's twin table
        against its independent-pipeline oracle. Returns the diverged
        vantages — [] is the passing result. Converges any stale
        vantages first (the oracle models a fully-converged daemon)."""
        if self.twin.stale:
            self.twin.converge()
        diverged = []
        for node in nodes if nodes is not None else list(self.twin.nodes):
            mine = self.twin.route_dbs.get(node)
            ref = self.oracle_route_db(node)
            if mine is None or ref is None:
                if (mine is None) != (ref is None):
                    diverged.append(node)
                continue
            if wire.dumps(mine.to_route_db(node)) != wire.dumps(
                ref.to_route_db(node)
            ):
                diverged.append(node)
        return diverged

    # -- scripted adjacency surgery ----------------------------------------

    def _adj_db(self, node: str):
        return self.gen.adj_dbs[node]

    def _withdraw(self, node: str, toward: str, sink: List) -> bool:
        """Remove every ``node``→``toward`` adjacency from the
        generator's evolving database, remembering it in ``sink`` for
        restore. Returns True when something was withdrawn."""
        db = self._adj_db(node)
        kept, pulled = [], []
        for adj in db.adjacencies:
            (pulled if adj.other_node_name == toward else kept).append(adj)
        if not pulled:
            return False
        self.gen.adj_dbs[node] = _dc_replace(db, adjacencies=tuple(kept))
        sink.extend((node, adj) for adj in pulled)
        return True

    def flap_link(self, a: str, b: str, converge: bool = True) -> None:
        """Withdraw BOTH directions of the a—b link (a real link flap
        floods two adjacency databases)."""
        sink = self._withdrawn.setdefault(self._link_key(a, b), [])
        for node, toward in ((a, b), (b, a)):
            if self._withdraw(node, toward, sink):
                self.apply(self.gen.emit_adjacency(node, kind=KIND_FLAP))
        if converge:
            self.twin.converge()

    def restore_link(self, a: str, b: str, converge: bool = True) -> None:
        sink = self._withdrawn.pop(self._link_key(a, b), [])
        for node, adj in sink:
            db = self._adj_db(node)
            self.gen.adj_dbs[node] = _dc_replace(
                db, adjacencies=db.adjacencies + (adj,)
            )
        for node in sorted({node for node, _ in sink}):
            self.apply(self.gen.emit_adjacency(node, kind=KIND_FLAP))
        if converge:
            self.twin.converge()

    @staticmethod
    def _link_key(a: str, b: str) -> Tuple[str, str]:
        return (a, b) if a <= b else (b, a)

    def set_metric(self, a: str, b: str, metric: int,
                   converge: bool = True) -> None:
        """Symmetric metric change on the a—b link."""
        for node, toward in ((a, b), (b, a)):
            db = self._adj_db(node)
            adjs = tuple(
                _dc_replace(adj, metric=metric)
                if adj.other_node_name == toward
                else adj
                for adj in db.adjacencies
            )
            if adjs != db.adjacencies:
                self.gen.adj_dbs[node] = _dc_replace(db, adjacencies=adjs)
                self.apply(self.gen.emit_adjacency(node, kind=KIND_METRIC))
        if converge:
            self.twin.converge()

    # -- drains ------------------------------------------------------------

    def drain(self, node: str, drained: bool = True,
              converge: bool = True) -> None:
        """Set/clear ``is_overloaded`` on one node (operational drain)."""
        db = self._adj_db(node)
        if db.is_overloaded != drained:
            self.gen.adj_dbs[node] = _dc_replace(db, is_overloaded=drained)
            self.apply(self.gen.emit_adjacency(node, kind=KIND_DRAIN))
        if converge:
            self.twin.converge()

    def drain_sequence(self, nodes: Sequence[str]) -> None:
        """Drain each node in order, fleet-converging between steps —
        the maintenance sequencing pattern (each wave must stay clean:
        drained nodes stop transiting but traffic keeps delivering)."""
        for node in nodes:
            self.drain(node, True)

    def undrain_sequence(self, nodes: Sequence[str]) -> None:
        for node in nodes:
            self.drain(node, False)

    # -- partitions --------------------------------------------------------

    def partition(self, group: Sequence[str], converge: bool = True) -> None:
        """Cut every link between ``group`` and the rest of the fabric
        (an area partition). ``heal_partition`` restores the cut."""
        inside = set(group)
        touched = set()
        for node in sorted(self.gen.adj_dbs):
            others = {
                adj.other_node_name
                for adj in self._adj_db(node).adjacencies
            }
            for other in sorted(others):
                if (node in inside) != (other in inside):
                    if self._withdraw(node, other, self._partition_cut):
                        touched.add(node)
        for node in sorted(touched):
            self.apply(self.gen.emit_adjacency(node, kind=KIND_FLAP))
        TWIN_COUNTERS["partitions"] += 1
        if converge:
            self.twin.converge()

    def heal_partition(self, converge: bool = True) -> None:
        cut, self._partition_cut = self._partition_cut, []
        for node, adj in cut:
            db = self._adj_db(node)
            self.gen.adj_dbs[node] = _dc_replace(
                db, adjacencies=db.adjacencies + (adj,)
            )
        for node in sorted({node for node, _ in cut}):
            self.apply(self.gen.emit_adjacency(node, kind=KIND_FLAP))
        if converge:
            self.twin.converge()

    # -- rolling restarts --------------------------------------------------

    def rolling_restart(self, nodes: Optional[Sequence[str]] = None
                        ) -> List[str]:
        """Restart each vantage in turn with graceful-restart
        semantics and bit-compare its held table against the rebuilt
        one (the LSDB is unchanged across a restart, so they must
        match). Returns the nodes whose tables diverged — [] is the
        passing result."""
        diverged = []
        for node in nodes if nodes is not None else list(self.twin.nodes):
            held = self.twin.restart_node(node)
            rebuilt = self.twin.route_dbs.get(node)
            if held is None or rebuilt is None:
                if held is not rebuilt:
                    diverged.append(node)
                continue
            if wire.dumps(held.to_route_db(node)) != wire.dumps(
                rebuilt.to_route_db(node)
            ):
                diverged.append(node)
        return diverged

    # -- defect injectors --------------------------------------------------

    def inject_micro_loop(self, a: str, b: str) -> None:
        """Seed a micro-loop: flap the a—b link but reconverge ONLY
        its endpoints. They re-route the long way around while every
        stale vantage still forwards into the flap — a cycle in the
        per-prefix forwarding graph that ``twin.analyze()`` must
        report. One full ``converge()`` heals it."""
        self.flap_link(a, b, converge=False)
        self.twin.converge([a, b])

    def inject_blackhole(self, node: str) -> None:
        """Seed a transient blackhole: ``node`` advertises a fresh
        prefix, but only ``node`` reconverges — every other vantage is
        missing a route to deliverable traffic until the next full
        wave."""
        db = self.gen.prefix_dbs[node]
        extra = _extra_prefix(self.gen._node_idx[node])
        if all(e.prefix != extra for e in db.prefix_entries):
            base = db.prefix_entries[0] if db.prefix_entries else None
            entry = (
                _dc_replace(base, prefix=extra)
                if base is not None
                else PrefixEntry(prefix=extra)
            )
            self.gen.prefix_dbs[node] = _dc_replace(
                db, prefix_entries=db.prefix_entries + (entry,)
            )
        self.apply(self.gen.emit_prefix(node))
        self.twin.converge([node])
