"""Whole-network digital twin: every node's control plane, one device.

A real Open/R deployment is N daemons each running Decision over
nearly the same flooded LSDB from their own vantage. ``FabricTwin``
models that fleet as ONE batched world per node on the tenant plane
(``ops.world_batch``):

- all vantages share the flooded structure — one ``LinkState`` +
  ``PrefixState``, one compiled ``EllGraph`` (the manager's
  vantage-view packing shares compile and patch across same-ls
  tenants), one journaled patch per injected event;
- vantages differ only in their source batch ({self} + neighbors) and
  optional vantage-local overload overrides (what-if drains);
- each injected event re-solves the whole fleet as one
  ``world_dispatch`` wave (zero retraces after fleet warmup — every
  vantage rides the same bucket executable), and the per-vantage
  views fan into ``decision.spf_solver.fleet_preload_views`` so the N
  ``build_route_db`` calls consume them with zero further device work.

On top of the solved per-node tables, ``twin.analyzer`` walks
next-hops across vantages for micro-loops and transient blackholes,
and ``twin.scenario`` scripts the event sequences (flaps, churn,
drain sequencing, partitions, rolling restarts) no single-daemon test
can express.
"""

from __future__ import annotations

import base64
import itertools
from dataclasses import replace as _dc_replace
from typing import Dict, List, Optional, Sequence

from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib import DecisionRouteDb
from openr_tpu.decision.spf_solver import SpfSolver, fleet_preload_views
from openr_tpu.graph.linkstate import LinkState
from openr_tpu.load.generator import LoadEvent
from openr_tpu.models.topologies import Topology
from openr_tpu.ops.world_batch import WorldManager
from openr_tpu.telemetry import get_registry, get_tracer
from openr_tpu.twin.analyzer import FleetReport, analyze_fleet
from openr_tpu.twin.metrics import TWIN_COUNTERS
from openr_tpu.types import AdjacencyDatabase, PrefixDatabase
from openr_tpu.utils import keys as keyutil
from openr_tpu.utils import wire

# tenant ids must stay unique per twin even when a manager is shared
# across twins (id() reuse after gc must never alias tenants)
_TWIN_SEQ = itertools.count(1)


def _pow2_at_least(x: int) -> int:
    p = 1
    while p < x:
        p *= 2
    return p


class FabricTwin:
    """N vantages over one shared LSDB, solved as one batched wave.

    The twin owns a dedicated ``WorldManager`` sized so the WHOLE
    fleet fits one bucket wave (``slots_per_bucket >= N``) — the
    one-dispatch-per-event contract would silently become two waves
    under the process-global manager's default 8 slots. Pass
    ``manager=`` to share one (e.g. several small twins in one test).
    """

    def __init__(
        self,
        topo: Topology,
        *,
        area: Optional[str] = None,
        solver_backend: str = "device",
        manager: Optional[WorldManager] = None,
        record_journal: bool = False,
    ):
        self.topo = topo
        self.area = area if area is not None else (topo.area or "0")
        self.nodes: List[str] = sorted(topo.adj_dbs)
        self._seq = next(_TWIN_SEQ)
        # opt-in (several twins share one process-wide flight journal,
        # so only the scenario under capture records): every applied
        # event journals a pub, every converge a wave mark, and the
        # starting databases seed the bundle's LSDB anchor — together
        # a post-mortem bundle replays this twin exactly
        self.record_journal = bool(record_journal)
        anchor: Dict[str, Dict[str, object]] = {}
        self.ls = LinkState(self.area)
        self.prefix_state = PrefixState()
        for name in self.nodes:
            db = topo.adj_dbs[name]
            if db.area != self.area:
                db = _dc_replace(db, area=self.area)
            self.ls.update_adjacency_database(db)
            if self.record_journal:
                anchor[keyutil.adj_key(name)] = {
                    "value_b64": base64.b64encode(
                        wire.dumps(db)).decode("ascii"),
                    "version": 1,
                    "originator": name,
                }
        for name in sorted(topo.prefix_dbs):
            pdb = topo.prefix_dbs[name]
            if pdb.area != self.area:
                pdb = _dc_replace(pdb, area=self.area)
            self.prefix_state.update_prefix_database(pdb)
            if self.record_journal:
                anchor[keyutil.prefix_db_key(name)] = {
                    "value_b64": base64.b64encode(
                        wire.dumps(pdb)).decode("ascii"),
                    "version": 1,
                    "originator": name,
                }
        if self.record_journal:
            from openr_tpu.telemetry.flight import get_flight_recorder

            get_flight_recorder().journal_anchor(self.area, anchor)
        if manager is None:
            manager = WorldManager(
                slots_per_bucket=_pow2_at_least(len(self.nodes)),
                max_resident=max(1, len(self.nodes)),
            )
        self.manager = manager
        self._backend = solver_backend
        self.solvers: Dict[str, SpfSolver] = {
            n: SpfSolver(n, backend=solver_backend) for n in self.nodes
        }
        self.route_dbs: Dict[str, DecisionRouteDb] = {}
        # vantage -> {node: overloaded} what-if views (cold-solved in
        # the same wave; see WorldManager._apply_override)
        self.overrides: Dict[str, Dict[str, bool]] = {}
        self.stale: set = set(self.nodes)
        self.events_applied = 0
        TWIN_COUNTERS["vantages"] += len(self.nodes)

    # -- event plane -------------------------------------------------------

    def apply_event(self, ev: LoadEvent) -> bool:
        """Apply one generated/scripted publication to the shared
        LSDB exactly the way ``Decision.process_publication`` would;
        every vantage goes stale until the next converge wave. Returns
        False for dropped/unknown events (a pure no-op)."""
        if ev.dropped or ev.payload is None:
            return False
        if keyutil.is_adj_key(ev.key):
            db = wire.loads(ev.payload, AdjacencyDatabase)
            if db.area != self.area:
                db = _dc_replace(db, area=self.area)
            self.ls.update_adjacency_database(db)
        elif keyutil.is_prefix_key(ev.key):
            pdb = wire.loads(ev.payload, PrefixDatabase)
            if pdb.area != self.area:
                pdb = _dc_replace(pdb, area=self.area)
            self.prefix_state.update_prefix_database(pdb)
        else:
            return False
        self.events_applied += 1
        TWIN_COUNTERS["events"] += 1
        self.stale.update(self.nodes)
        TWIN_COUNTERS["stale_vantages"] = len(self.stale)
        if self.record_journal:
            from openr_tpu.telemetry.flight import get_flight_recorder

            get_flight_recorder().journal_note(
                self.area,
                ev.key,
                value_b64=base64.b64encode(ev.payload).decode("ascii"),
                version=ev.version,
                originator=ev.node,
            )
        return True

    # -- converge plane ----------------------------------------------------

    def _tid(self, node: str) -> str:
        return f"twin/{self._seq}/{node}"

    def converge(
        self, vantages: Optional[Sequence[str]] = None
    ) -> Dict[str, DecisionRouteDb]:
        """One fleet reconvergence wave: solve the given vantages (all
        stale ones by default) as ONE batched tenant dispatch, preload
        the views, and rebuild each vantage's RIB. Converging a strict
        subset deliberately leaves the rest serving mixed-epoch tables
        — that is how scenarios model in-flight reconvergence for the
        analyzer."""
        nodes = (
            [n for n in self.nodes if n in self.stale]
            if vantages is None
            else [n for n in self.nodes if n in set(vantages)]
        )
        if not nodes:
            return {}
        tracer = get_tracer()
        trace = tracer.start(origin="twin.converge")
        tracer.activate(trace)
        span = tracer.span_active("twin.fleet_converge")
        out: Dict[str, DecisionRouteDb] = {}
        try:
            with get_registry().timed("twin.converge_ms"):
                views = self.manager.solve_views(
                    [
                        (
                            self._tid(n),
                            self.ls,
                            n,
                            self.overrides.get(n),
                        )
                        for n in nodes
                    ]
                )
                fleet_preload_views(self.ls, views)
                area_ls = {self.area: self.ls}
                for n in nodes:
                    db = self.solvers[n].build_route_db(
                        n, area_ls, self.prefix_state
                    )
                    if db is None:
                        self.route_dbs.pop(n, None)
                    else:
                        self.route_dbs[n] = db
                        out[n] = db
                    self.stale.discard(n)
            TWIN_COUNTERS["waves"] += 1
            TWIN_COUNTERS["vantage_solves"] += len(nodes)
            TWIN_COUNTERS["stale_vantages"] = len(self.stale)
        finally:
            tracer.end_span_active(
                span, vantages=len(nodes), stale=len(self.stale)
            )
            tracer.deactivate()
            tracer.finish(trace)
        if self.record_journal:
            from openr_tpu.telemetry.flight import get_flight_recorder

            get_flight_recorder().journal_mark(
                "wave",
                window="twin.converge",
                vantages=list(nodes),
                stale=len(self.stale),
            )
        return out

    def step(self, ev: LoadEvent) -> Dict[str, DecisionRouteDb]:
        """Apply one event and reconverge the whole fleet (one wave)."""
        self.apply_event(ev)
        return self.converge()

    # -- what-if / restart seams -------------------------------------------

    def set_override(
        self, vantage: str, overloads: Optional[Dict[str, bool]]
    ) -> None:
        """Give ``vantage`` a local overload view layered over the
        shared LSDB (None/empty clears it). The vantage goes stale; it
        cold-solves inside the next wave — same executable, no
        retrace. The vantage also gets a fresh solver: its view cache
        keys on (topology_version, root), and an override moves the
        solve without moving the LSDB version, so a kept solver would
        serve the pre-override view and strand the preloaded one."""
        if overloads:
            self.overrides[vantage] = dict(overloads)
        else:
            self.overrides.pop(vantage, None)
        self.solvers[vantage] = SpfSolver(vantage, backend=self._backend)
        self.stale.add(vantage)
        TWIN_COUNTERS["stale_vantages"] = len(self.stale)

    def restart_node(self, node: str) -> Optional[DecisionRouteDb]:
        """Rolling-restart one vantage with graceful-restart
        semantics: the held RIB keeps serving (it is never cleared)
        while the vantage's solver state and tenant world are dropped
        and warm-booted from the shared LSDB. Returns the held table;
        on an unchanged LSDB the rebuilt RIB must be bit-identical to
        it — the PR 10 graceful-restart contract, checkable
        fleet-wide."""
        held = self.route_dbs.get(node)
        self.manager.drop(self._tid(node))
        self.solvers[node] = SpfSolver(node, backend=self._backend)
        self.stale.add(node)
        self.converge([node])
        TWIN_COUNTERS["restarts"] += 1
        return held

    # -- analysis ----------------------------------------------------------

    def analyze(self) -> FleetReport:
        """Run the fleet analyzer over the CURRENT per-vantage tables
        (mixed epochs included — that is the point)."""
        report = analyze_fleet(
            self.route_dbs, self.ls, self.prefix_state
        )
        if self.record_journal:
            from openr_tpu.telemetry.flight import get_flight_recorder

            get_flight_recorder().journal_mark(
                "analysis",
                micro_loops=len(report.loops()),
                blackholes=len(report.blackholes()),
                clean=report.clean,
                route_digests=self.route_digests(),
            )
        return report

    def route_digests(self) -> Dict[str, int]:
        """FNV-1a digest of every vantage's serialized RouteDatabase —
        the bundle-embedded ground truth for the replayer's
        bit-identical determinism check."""
        from openr_tpu.telemetry.flight import fnv1a

        return {
            n: fnv1a(wire.dumps(db.to_route_db(n)))
            for n, db in sorted(self.route_dbs.items())
        }

    def close(self) -> None:
        """Release the fleet's tenant worlds (device slots)."""
        for n in self.nodes:
            self.manager.drop(self._tid(n))
        TWIN_COUNTERS["vantages"] -= len(self.nodes)
