"""Whole-network digital twin: every node's control plane, one device.

``FabricTwin`` models an N-node fabric as one batched tenant world
per vantage over one shared LSDB (one compiled graph, one journaled
patch, one dispatch wave per event); ``ScenarioDriver`` scripts the
operational sequences (flaps, drains, partitions, rolling restarts)
on top of seeded background load; ``analyze_fleet`` walks next hops
across vantages for micro-loops and transient blackholes.
"""

from openr_tpu.twin.analyzer import (
    KIND_BLACKHOLE,
    KIND_MICRO_LOOP,
    Finding,
    FleetReport,
    analyze_fleet,
)
from openr_tpu.twin.fabric import FabricTwin
from openr_tpu.twin.metrics import TWIN_COUNTERS
from openr_tpu.twin.replay import ReplayVerdict, ScenarioReplayer
from openr_tpu.twin.scenario import FAULT_TWIN_INJECT, ScenarioDriver

__all__ = [
    "FabricTwin",
    "ScenarioDriver",
    "ScenarioReplayer",
    "ReplayVerdict",
    "FleetReport",
    "Finding",
    "analyze_fleet",
    "FAULT_TWIN_INJECT",
    "TWIN_COUNTERS",
    "KIND_MICRO_LOOP",
    "KIND_BLACKHOLE",
]
