"""Incident replay: turn a post-mortem bundle into a deterministic
twin run and a reproduced/not-reproduced verdict.

A bundle written by ``FlightRecorder.dump_postmortem`` is
self-contained (DeltaPath's insight, via the state plane's WAL
semantics): the ``journal`` section carries an LSDB **anchor** (the
rolling base — every pub evicted from the journal ring folded down,
digest-stamped with FNV-1a) plus the **ring slice** of adopted
post-CRDT publications and wave marks recorded up to the freeze.
``base + slice`` is therefore the complete adopted history of the
frozen window, and replaying it is exactly the state plane's
checkpoint+journal recovery fold (``state.plane.replay_journal``).

``ScenarioReplayer`` runs that fold in a fresh process:

1. decode the anchor into an ``LsdbCheckpoint``, verify its FNV graph
   digest (a corrupt or hand-edited bundle fails closed), and rebuild
   the starting topology via ``replay_journal`` — one recovery
   semantics shared with warm boot;
2. feed the slice through a ``FabricTwin``: pubs apply to the shared
   LSDB, each recorded ``wave`` mark converges EXACTLY the vantages
   the original wave solved — one dispatch wave per recorded debounce
   window, so mixed-epoch states (the interesting ones: micro-loops
   live between a partial converge and the heal wave) reproduce
   bit-for-bit;
3. re-run the micro-loop/blackhole analyzer at every recorded
   ``analysis`` mark and at the end, and emit a ``ReplayVerdict`` —
   anomaly class reproduced or not, per-window divergence diff
   against the recorded counters/digests, and the final per-vantage
   RouteDatabase digests (two replays of one bundle must agree
   bit-for-bit; so must replay-vs-original when the bundle carries
   recorded digests).

Ordering hazard (see ARCHITECTURE "Incident replay plane"): pubs
recorded after the last wave mark were still pending in the debounce
window at freeze time — they are applied but deliberately left
unconverged, mirroring the frozen process. A bundle whose ring
evicted *wave marks* (``base_seq > 0`` with fewer marks than waves)
has lost window boundaries; the replay still converges to the same
final LSDB but intermediate mixed-epoch states may differ —
``anchor_moved`` flags it in the verdict.
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from openr_tpu.load.generator import LoadEvent
from openr_tpu.models.topologies import Topology
from openr_tpu.state.plane import JournalRecord, LsdbCheckpoint, replay_journal
from openr_tpu.telemetry import get_registry
from openr_tpu.telemetry.flight import _lsdb_digest, fnv1a, load_bundle
from openr_tpu.twin.analyzer import KIND_BLACKHOLE, KIND_MICRO_LOOP
from openr_tpu.twin.fabric import FabricTwin
from openr_tpu.types import AdjacencyDatabase, PrefixDatabase, Value
from openr_tpu.types.kvstore import TTL_INFINITY
from openr_tpu.utils import keys as keyutil
from openr_tpu.utils import wire


@dataclass
class ReplayVerdict:
    """What a replay concluded. ``reproduced`` is the headline: every
    anomaly class the original run recorded showed up again."""

    reproduced: bool = False
    recorded_classes: List[str] = field(default_factory=list)
    replayed_classes: List[str] = field(default_factory=list)
    windows: int = 0
    pubs_applied: int = 0
    trailing_pubs: int = 0
    anchor_moved: bool = False
    divergence: List[Dict[str, Any]] = field(default_factory=list)
    route_digests: Dict[str, int] = field(default_factory=dict)
    digests_match_recorded: Optional[bool] = None
    errors: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "reproduced": self.reproduced,
            "recorded_classes": list(self.recorded_classes),
            "replayed_classes": list(self.replayed_classes),
            "windows": self.windows,
            "pubs_applied": self.pubs_applied,
            "trailing_pubs": self.trailing_pubs,
            "anchor_moved": self.anchor_moved,
            "divergence": list(self.divergence),
            "route_digests": dict(self.route_digests),
            "digests_match_recorded": self.digests_match_recorded,
            "errors": list(self.errors),
        }


def _decode_value(rec: Dict[str, Any]) -> Value:
    payload = base64.b64decode(rec["value_b64"])
    version = int(rec.get("version", 1))
    originator = rec.get("originator", "")
    return Value(
        version=version,
        originator_id=originator,
        value=payload,
        ttl=TTL_INFINITY,
        hash=wire.generate_hash(version, originator, payload),
    )


class ScenarioReplayer:
    """Ingest one bundle, drive one twin, emit one verdict."""

    def __init__(self, bundle: Dict[str, Any],
                 solver_backend: str = "device"):
        self.bundle = bundle
        self._backend = solver_backend

    @classmethod
    def from_path(cls, path: str,
                  solver_backend: str = "device") -> "ScenarioReplayer":
        return cls(load_bundle(path), solver_backend=solver_backend)

    # -- anchor reconstruction ---------------------------------------

    def _anchor_lsdb(self, verdict: ReplayVerdict) -> Dict[str, Dict[str, Value]]:
        journal = self.bundle.get("journal") or {}
        anchor = journal.get("anchor") or {}
        raw_lsdb = anchor.get("lsdb") or {}
        recorded_digest = anchor.get("graph_digest")
        if recorded_digest is not None:
            actual = _lsdb_digest(raw_lsdb)
            if actual != recorded_digest:
                raise ValueError(
                    f"anchor digest mismatch: bundle says "
                    f"{recorded_digest}, LSDB hashes to {actual} — "
                    f"corrupt or hand-edited bundle"
                )
        verdict.anchor_moved = int(journal.get("base_seq", 0) or 0) > 0
        # one synthetic checkpoint + zero journal records: the anchor
        # base is already fully folded, so the recovery fold reduces to
        # decoding it — but going through replay_journal keeps replay
        # on the state plane's recovery semantics
        ckpt = LsdbCheckpoint(
            seq=0,
            key_vals_by_area={
                area: {k: _decode_value(rec) for k, rec in kv.items()}
                for area, kv in raw_lsdb.items()
            },
        )
        return replay_journal(ckpt, [])

    def _build_twin(self, lsdb: Dict[str, Dict[str, Value]]) -> FabricTwin:
        if not lsdb:
            raise ValueError("bundle has no journal anchor — nothing to replay")
        # one twin per area is the twin's model; bundles from a
        # single-fabric pipeline carry one area
        area = sorted(lsdb)[0]
        adj_dbs: Dict[str, AdjacencyDatabase] = {}
        prefix_dbs: Dict[str, PrefixDatabase] = {}
        for key, value in lsdb[area].items():
            if value.value is None:
                continue
            if keyutil.is_adj_key(key):
                db = wire.loads(value.value, AdjacencyDatabase)
                adj_dbs[db.this_node_name] = db
            elif keyutil.is_prefix_key(key):
                pdb = wire.loads(value.value, PrefixDatabase)
                prefix_dbs[pdb.this_node_name] = pdb
        topo = Topology(
            name="replay",
            area=area,
            adj_dbs=adj_dbs,
            prefix_dbs=prefix_dbs,
        )
        return FabricTwin(
            topo, area=area, solver_backend=self._backend
        )

    # -- replay --------------------------------------------------------

    def replay(self) -> ReplayVerdict:
        verdict = ReplayVerdict()
        verdict.recorded_classes = self._recorded_classes()
        lsdb = self._anchor_lsdb(verdict)
        twin = self._build_twin(lsdb)
        records = (self.bundle.get("journal") or {}).get("records") or []
        pending = 0
        try:
            for rec in records:
                if "mark" in rec:
                    self._replay_mark(twin, rec, verdict, pending)
                    if rec["mark"] == "wave":
                        verdict.windows += 1
                        pending = 0
                    continue
                ev = LoadEvent(
                    seq=int(rec.get("seq", 0)),
                    kind="replay",
                    node=rec.get("originator", ""),
                    key=rec["key"],
                    payload=base64.b64decode(rec["value_b64"]),
                    version=int(rec.get("version", 1)),
                )
                if twin.apply_event(ev):
                    verdict.pubs_applied += 1
                    pending += 1
            verdict.trailing_pubs = pending
            report = twin.analyze()
            verdict.replayed_classes = sorted(
                {f.kind for f in report.findings}
            )
            verdict.route_digests = twin.route_digests()
            recorded_digests = self._last_recorded_digests()
            if recorded_digests is not None:
                verdict.digests_match_recorded = recorded_digests == {
                    str(k): v for k, v in verdict.route_digests.items()
                }
            verdict.reproduced = bool(verdict.recorded_classes) and set(
                verdict.recorded_classes
            ) <= set(verdict.replayed_classes)
            get_registry().counter_bump("twin.replays")
            if verdict.reproduced:
                get_registry().counter_bump("twin.replays_reproduced")
        finally:
            twin.close()
        return verdict

    def _replay_mark(self, twin: FabricTwin, rec: Dict[str, Any],
                     verdict: ReplayVerdict, pending: int) -> None:
        kind = rec["mark"]
        if kind == "wave":
            vantages = rec.get("vantages") or None
            twin.converge(vantages)
            stale = rec.get("stale")
            if stale is not None and stale != len(twin.stale):
                verdict.divergence.append({
                    "window": verdict.windows,
                    "field": "stale_vantages",
                    "recorded": stale,
                    "replayed": len(twin.stale),
                })
        elif kind == "analysis":
            report = twin.analyze()
            for name, recorded in (
                ("micro_loops", rec.get("micro_loops")),
                ("blackholes", rec.get("blackholes")),
            ):
                if recorded is None:
                    continue
                replayed = len(
                    report.loops() if name == "micro_loops"
                    else report.blackholes()
                )
                if replayed != recorded:
                    verdict.divergence.append({
                        "window": verdict.windows,
                        "field": name,
                        "recorded": recorded,
                        "replayed": replayed,
                    })
            recorded_digests = rec.get("route_digests")
            if recorded_digests:
                mine = {str(k): v for k, v in twin.route_digests().items()}
                theirs = {str(k): v for k, v in recorded_digests.items()}
                if mine != theirs:
                    verdict.divergence.append({
                        "window": verdict.windows,
                        "field": "route_digests",
                        "recorded": len(theirs),
                        "replayed": sum(
                            1 for k in mine if mine[k] == theirs.get(k)
                        ),
                    })

    # -- recorded ground truth -----------------------------------------

    def _marks(self, kind: str) -> List[Dict[str, Any]]:
        records = (self.bundle.get("journal") or {}).get("records") or []
        return [r for r in records if r.get("mark") == kind]

    def _recorded_classes(self) -> List[str]:
        """The anomaly classes the original run recorded: analyzer
        counts from ``analysis`` marks, plus the trigger name itself
        when it names a class."""
        classes = set()
        for rec in self._marks("analysis"):
            if rec.get("micro_loops"):
                classes.add(KIND_MICRO_LOOP)
            if rec.get("blackholes"):
                classes.add(KIND_BLACKHOLE)
        trigger = self.bundle.get("trigger", "")
        if trigger in (KIND_MICRO_LOOP, KIND_BLACKHOLE):
            classes.add(trigger)
        return sorted(classes)

    def _last_recorded_digests(self) -> Optional[Dict[str, int]]:
        marks = self._marks("analysis")
        for rec in reversed(marks):
            digests = rec.get("route_digests")
            if digests:
                return {str(k): v for k, v in digests.items()}
        return None


def replay_digest(verdict: ReplayVerdict) -> int:
    """One FNV-1a number over the verdict's per-vantage digests — what
    'bit-identical twice in a row' compares."""
    blob = json.dumps(
        sorted(verdict.route_digests.items()), separators=(",", ":")
    )
    return fnv1a(blob.encode())


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m openr_tpu.twin.replay <bundle> [--json] [--backend B]``
    — the fresh-process entry `tools/replay_smoke.py` and `breeze
    monitor replay` both drive. Exit 0 when the recorded anomaly class
    reproduced (or the bundle recorded a clean run and replay stayed
    clean), 1 otherwise."""
    import argparse

    ap = argparse.ArgumentParser(prog="openr_tpu.twin.replay")
    ap.add_argument("bundle")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--backend", default="device")
    ap.add_argument("--twice", action="store_true",
                    help="replay twice and require bit-identical "
                         "per-vantage digests")
    args = ap.parse_args(argv)
    replayer = ScenarioReplayer.from_path(args.bundle,
                                          solver_backend=args.backend)
    verdict = replayer.replay()
    deterministic = None
    if args.twice:
        second = ScenarioReplayer.from_path(
            args.bundle, solver_backend=args.backend
        ).replay()
        deterministic = replay_digest(verdict) == replay_digest(second)
    out = verdict.to_dict()
    if deterministic is not None:
        out["deterministic"] = deterministic
    ok = (
        verdict.reproduced
        or (not verdict.recorded_classes and not verdict.replayed_classes)
    ) and not verdict.errors and deterministic is not False
    out["ok"] = ok
    if args.as_json:
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        print(f"bundle:     {args.bundle}")
        print(f"trigger:    {replayer.bundle.get('trigger')} "
              f"({replayer.bundle.get('reason', '')})")
        print(f"windows:    {verdict.windows} "
              f"(+{verdict.trailing_pubs} trailing pubs)")
        print(f"recorded:   {', '.join(verdict.recorded_classes) or 'clean'}")
        print(f"replayed:   {', '.join(verdict.replayed_classes) or 'clean'}")
        print(f"reproduced: {verdict.reproduced}")
        if verdict.digests_match_recorded is not None:
            print(f"digests match recorded: "
                  f"{verdict.digests_match_recorded}")
        if deterministic is not None:
            print(f"deterministic: {deterministic}")
        for d in verdict.divergence:
            print(f"  divergence w{d['window']} {d['field']}: "
                  f"recorded {d['recorded']} vs replayed {d['replayed']}")
    return 0 if ok else 1


if __name__ == "__main__":  # pragma: no cover - CLI shim
    raise SystemExit(main())
