"""Fleet analyzer: walk next-hops across vantages to find the two
reconvergence pathologies no single-daemon view can see.

Given every vantage's computed RIB over ONE shared LSDB snapshot, each
destination prefix induces a directed forwarding graph: vantage ``v``
points at the neighbor nodes named by its ECMP next hops for that
prefix. Two defect classes fall out of walking it:

- **micro-loop** — a cycle in the per-prefix forwarding graph. On a
  fully converged fleet this cannot happen (every next hop strictly
  decreases the shared SPF distance), so a cycle is the signature of
  *mixed-epoch* tables: some vantages re-solved after an event while
  others still forward on the pre-event snapshot.
- **transient blackhole** — a vantage that should be able to deliver
  but drops instead: it has no route for a prefix that is reachable
  from it in the current topology (stale table missing a fresh
  advertisement), or its next hop names a neighbor the current
  topology no longer connects it to (fresh withdrawal, stale route —
  the packet dies on the dead link).

Reachability is judged on the CURRENT LinkState: bidirectional up
links only, and overloaded (drained) nodes do not transit — matching
the SPF semantics the route tables themselves were built under. A
prefix that is genuinely unreachable from a vantage is NOT a
blackhole; the analyzer only flags deliverable traffic that a
mixed-epoch fleet would drop or spin.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from openr_tpu.twin.metrics import TWIN_COUNTERS

KIND_MICRO_LOOP = "micro_loop"
KIND_BLACKHOLE = "blackhole"


@dataclass(frozen=True)
class Finding:
    """One defect: the prefix it affects and the walk that exhibits it
    (a cycle for micro-loops; ``(vantage,)`` for a missing route or
    ``(vantage, dead_next_hop)`` for a stale next hop)."""

    kind: str
    prefix: str
    path: Tuple[str, ...]


@dataclass
class FleetReport:
    """One analyzer pass over the fleet's route tables."""

    findings: List[Finding] = field(default_factory=list)
    prefixes: int = 0
    vantages: int = 0

    @property
    def clean(self) -> bool:
        return not self.findings

    def loops(self) -> List[Finding]:
        return [f for f in self.findings if f.kind == KIND_MICRO_LOOP]

    def blackholes(self) -> List[Finding]:
        return [f for f in self.findings if f.kind == KIND_BLACKHOLE]

    def to_dict(self) -> dict:
        return {
            "clean": self.clean,
            "prefixes": self.prefixes,
            "vantages": self.vantages,
            "micro_loops": len(self.loops()),
            "blackholes": len(self.blackholes()),
            "findings": [
                {"kind": f.kind, "prefix": f.prefix, "path": list(f.path)}
                for f in self.findings
            ],
        }


def _up_neighbors(ls) -> Dict[str, Set[str]]:
    """Current bidirectional up-link neighbor sets per node."""
    return {
        n: {
            link.other_node(n)
            for link in ls.links_from_node(n)
            if link.is_up()
        }
        for n in ls.nodes()
    }


def _reachable_to(
    dsts: Set[str],
    neighbors: Dict[str, Set[str]],
    overloaded: Dict[str, bool],
) -> Set[str]:
    """Nodes with SOME deliverable path to any node in ``dsts`` over
    the current topology: links are symmetric, and an overloaded node
    may source or sink traffic but never transit (the SPF overload
    contract)."""
    seen = {d for d in dsts if d in neighbors}
    queue = deque(seen)
    while queue:
        u = queue.popleft()
        if u not in dsts and overloaded.get(u):
            continue  # drained: no transit through it
        for v in neighbors.get(u, ()):
            if v not in seen:
                seen.add(v)
                queue.append(v)
    return seen


def analyze_fleet(
    route_dbs: Dict[str, object],
    ls,
    prefix_state,
    vantages: Optional[Sequence[str]] = None,
) -> FleetReport:
    """Walk every (prefix, vantage) forwarding graph and report
    micro-loops and transient blackholes. ``route_dbs`` maps vantage
    name -> DecisionRouteDb (mixed epochs allowed — that is the
    point); ``ls``/``prefix_state`` are the CURRENT shared truth the
    walks are judged against."""
    names = sorted(route_dbs) if vantages is None else sorted(vantages)
    neighbors = _up_neighbors(ls)
    overloaded = {n: ls.is_node_overloaded(n) for n in ls.nodes()}
    findings: List[Finding] = []
    prefix_map = prefix_state.prefixes()
    for prefix in sorted(prefix_map, key=lambda p: p.to_str()):
        pstr = prefix.to_str()
        advertisers = {na[0] for na in prefix_map[prefix]}
        deliverable = _reachable_to(advertisers, neighbors, overloaded)
        succ: Dict[str, Set[str]] = {}
        for v in names:
            if v in advertisers:
                continue  # delivers locally
            db = route_dbs.get(v)
            entry = (
                db.unicast_routes.get(prefix) if db is not None else None
            )
            hops = (
                {
                    nh.neighbor_node_name
                    for nh in entry.nexthops
                    if nh.neighbor_node_name
                }
                if entry is not None
                else set()
            )
            if not hops:
                if v in deliverable:
                    # stale table missing a deliverable prefix
                    findings.append(
                        Finding(KIND_BLACKHOLE, pstr, (v,))
                    )
                continue
            for u in sorted(hops):
                if u not in neighbors.get(v, ()):
                    # stale next hop over a now-dead link
                    findings.append(
                        Finding(KIND_BLACKHOLE, pstr, (v, u))
                    )
            succ[v] = {u for u in hops if u in neighbors.get(v, ())}
        findings.extend(
            Finding(KIND_MICRO_LOOP, pstr, cycle)
            for cycle in _cycles(names, succ, advertisers)
        )
    TWIN_COUNTERS["analyses"] += 1
    TWIN_COUNTERS["loops_found"] += sum(
        1 for f in findings if f.kind == KIND_MICRO_LOOP
    )
    TWIN_COUNTERS["blackholes_found"] += sum(
        1 for f in findings if f.kind == KIND_BLACKHOLE
    )
    return FleetReport(
        findings=findings,
        prefixes=len(prefix_map),
        vantages=len(names),
    )


def _cycles(
    names: Sequence[str],
    succ: Dict[str, Set[str]],
    advertisers: Set[str],
) -> List[Tuple[str, ...]]:
    """Cycles in one prefix's forwarding graph (iterative colored DFS;
    a walk reaching an advertiser has delivered and stops). Each
    distinct cycle node-set reports once."""
    color: Dict[str, int] = {}  # 1 = on stack, 2 = done
    out: List[Tuple[str, ...]] = []
    seen_cycles: Set[frozenset] = set()
    for start in names:
        if color.get(start) or start in advertisers:
            continue
        color[start] = 1
        path = [start]
        stack = [(start, iter(sorted(succ.get(start, ()))))]
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if nxt in advertisers:
                    continue  # delivered
                c = color.get(nxt, 0)
                if c == 1:
                    cycle = tuple(path[path.index(nxt):]) + (nxt,)
                    key = frozenset(cycle)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(cycle)
                elif c == 0:
                    color[nxt] = 1
                    path.append(nxt)
                    stack.append(
                        (nxt, iter(sorted(succ.get(nxt, ()))))
                    )
                    advanced = True
                    break
            if not advanced:
                color[node] = 2
                stack.pop()
                path.pop()
    return out
