"""PrefixManager: owns the prefixes this node advertises into the LSDB.

Behavioral parity with the reference ``openr/prefix-manager/PrefixManager``:
- advertise/withdraw/sync per PrefixType (LOOPBACK, CONFIG, BGP, ...)
  (reference: PrefixManager.h:72 advertisePrefixes)
- serializes to per-prefix KvStore keys ``prefix:<node>:<area>:[<prefix>]``
  via the KvStore client (persist + TTL refresh)
- accepts requests through a queue (PrefixEvent) and via direct API
- cross-area re-distribution of Decision's best routes is handled by the
  Decision+PrefixManager pair in the reference; tracked as future work
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import IpPrefix, PrefixDatabase, PrefixEntry, PrefixType
from openr_tpu.utils import keys as keyutil
from openr_tpu.utils import wire
from openr_tpu.utils.eventbase import OpenrEventBase


class PrefixEventType(enum.IntEnum):
    ADD_PREFIXES = 1
    WITHDRAW_PREFIXES = 2
    SYNC_PREFIXES_BY_TYPE = 3
    WITHDRAW_PREFIXES_BY_TYPE = 4


@dataclass
class PrefixEvent:
    event_type: PrefixEventType
    type: Optional[PrefixType] = None
    prefixes: List[PrefixEntry] = field(default_factory=list)


class PrefixManager:
    def __init__(
        self,
        my_node_name: str,
        kvstore_client,
        prefix_updates_queue: Optional[ReplicateQueue] = None,
        areas: Optional[List[str]] = None,
        per_prefix_keys: bool = True,
    ):
        self.my_node_name = my_node_name
        self.evb = OpenrEventBase(name=f"prefixmgr:{my_node_name}")
        self._client = kvstore_client
        self._areas = areas or ["0"]
        self._per_prefix_keys = per_prefix_keys
        # (type, prefix) -> entry
        self._prefixes: Dict[Tuple[PrefixType, IpPrefix], PrefixEntry] = {}
        self._advertised_keys: Dict[str, str] = {}  # key -> area
        if prefix_updates_queue is not None:
            self.evb.add_queue_reader(
                prefix_updates_queue.get_reader(f"pm:{my_node_name}"),
                self._on_event,
            )

    def start(self) -> None:
        self.evb.run_in_thread()

    def stop(self) -> None:
        self.evb.stop()
        self.evb.join()

    # -- queue interface --------------------------------------------------

    def _on_event(self, event: PrefixEvent) -> None:
        if event.event_type == PrefixEventType.ADD_PREFIXES:
            self._advertise(event.prefixes)
        elif event.event_type == PrefixEventType.WITHDRAW_PREFIXES:
            self._withdraw([e.prefix for e in event.prefixes])
        elif event.event_type == PrefixEventType.SYNC_PREFIXES_BY_TYPE:
            assert event.type is not None
            self._sync_by_type(event.type, event.prefixes)
        elif event.event_type == PrefixEventType.WITHDRAW_PREFIXES_BY_TYPE:
            assert event.type is not None
            self._withdraw(
                [
                    p
                    for (t, p) in list(self._prefixes)
                    if t == event.type
                ]
            )

    # -- public API (thread-safe) -----------------------------------------

    def advertise_prefixes(self, entries: List[PrefixEntry]) -> None:
        self.evb.call_and_wait(lambda: self._advertise(entries))

    def withdraw_prefixes(self, prefixes: List[IpPrefix]) -> None:
        self.evb.call_and_wait(lambda: self._withdraw(prefixes))

    def sync_prefixes_by_type(
        self, prefix_type: PrefixType, entries: List[PrefixEntry]
    ) -> None:
        self.evb.call_and_wait(lambda: self._sync_by_type(prefix_type, entries))

    def get_prefixes(self) -> List[PrefixEntry]:
        return self.evb.call_and_wait(
            lambda: sorted(self._prefixes.values(), key=lambda e: e.prefix)
        )

    # -- internals --------------------------------------------------------

    def _advertise(self, entries: List[PrefixEntry]) -> None:
        """reference: PrefixManager.cpp advertisePrefixesImpl."""
        for entry in entries:
            self._prefixes[(entry.type, entry.prefix)] = entry
        self._update_kvstore()

    def _withdraw(self, prefixes: List[IpPrefix]) -> None:
        for key in [k for k in self._prefixes if k[1] in set(prefixes)]:
            del self._prefixes[key]
        self._update_kvstore()

    def _sync_by_type(
        self, prefix_type: PrefixType, entries: List[PrefixEntry]
    ) -> None:
        for key in [k for k in self._prefixes if k[0] == prefix_type]:
            del self._prefixes[key]
        for entry in entries:
            self._prefixes[(prefix_type, entry.prefix)] = entry
        self._update_kvstore()

    def _update_kvstore(self) -> None:
        wanted: Dict[str, Tuple[str, bytes]] = {}
        for area in self._areas:
            if self._per_prefix_keys:
                for (_, prefix), entry in self._prefixes.items():
                    key = keyutil.per_prefix_key(
                        self.my_node_name, area, prefix
                    )
                    db = PrefixDatabase(
                        this_node_name=self.my_node_name,
                        prefix_entries=(entry,),
                        area=area,
                    )
                    wanted[key] = (area, wire.dumps(db))
            else:
                key = keyutil.prefix_db_key(self.my_node_name)
                db = PrefixDatabase(
                    this_node_name=self.my_node_name,
                    prefix_entries=tuple(
                        e
                        for _, e in sorted(
                            self._prefixes.items(),
                            key=lambda kv: kv[0][1],
                        )
                    ),
                    area=area,
                )
                wanted[key] = (area, wire.dumps(db))

        # withdraw keys that are no longer advertised: flood the delete
        # marker so other Decisions drop the entries
        for key, area in list(self._advertised_keys.items()):
            if key not in wanted:
                parsed = keyutil.parse_per_prefix_key(key)
                delete_db = PrefixDatabase(
                    this_node_name=self.my_node_name,
                    prefix_entries=(
                        (PrefixEntry(prefix=parsed[2]),) if parsed else ()
                    ),
                    delete_prefix=True,
                    area=area,
                )
                self._client.set_key(area, key, wire.dumps(delete_db))
                self._client.unset_key(area, key)
                del self._advertised_keys[key]

        for key, (area, payload) in wanted.items():
            self._client.persist_key(area, key, payload)
            self._advertised_keys[key] = area
