"""PrefixManager: owns the prefixes this node advertises into the LSDB.

Behavioral parity with the reference ``openr/prefix-manager/PrefixManager``:
- advertise/withdraw/sync per PrefixType (LOOPBACK, CONFIG, BGP, ...)
  (reference: PrefixManager.h:72 advertisePrefixes)
- serializes to per-prefix KvStore keys ``prefix:<node>:<area>:[<prefix>]``
  via the KvStore client (persist + TTL refresh)
- accepts requests through a queue (PrefixEvent) and via direct API
- cross-area re-distribution: subscribes to Decision's route updates and
  re-originates each best route into the areas it was *not* learned from,
  as a ``PrefixType.RIB`` entry with the source area appended to
  ``area_stack`` (loop prevention: never advertised into any area already
  on the stack). Reference: PrefixManager consuming
  decisionRouteUpdatesQueue + areaStack loop suppression
  (openr/prefix-manager/PrefixManager.cpp, SURVEY §2.1).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import IpPrefix, PrefixDatabase, PrefixEntry, PrefixType
from openr_tpu.types.lsdb import PrefixMetrics
from openr_tpu.utils import keys as keyutil
from openr_tpu.utils import wire
from openr_tpu.utils.constants import (
    DEFAULT_PATH_PREFERENCE,
    DEFAULT_SOURCE_PREFERENCE,
    KVSTORE_TOMBSTONE_TTL_MS,
)
from openr_tpu.utils.eventbase import OpenrEventBase


class PrefixEventType(enum.IntEnum):
    ADD_PREFIXES = 1
    WITHDRAW_PREFIXES = 2
    SYNC_PREFIXES_BY_TYPE = 3
    WITHDRAW_PREFIXES_BY_TYPE = 4


@dataclass
class PrefixEvent:
    event_type: PrefixEventType
    type: Optional[PrefixType] = None
    prefixes: List[PrefixEntry] = field(default_factory=list)


class PrefixManager:
    def __init__(
        self,
        my_node_name: str,
        kvstore_client,
        prefix_updates_queue: Optional[ReplicateQueue] = None,
        decision_route_updates_queue: Optional[ReplicateQueue] = None,
        areas: Optional[List[str]] = None,
        per_prefix_keys: bool = True,
    ):
        self.my_node_name = my_node_name
        self.evb = OpenrEventBase(name=f"prefixmgr:{my_node_name}")
        self._client = kvstore_client
        self._areas = areas or ["0"]
        self._per_prefix_keys = per_prefix_keys
        # (type, prefix) -> entry
        self._prefixes: Dict[Tuple[PrefixType, IpPrefix], PrefixEntry] = {}
        # cross-area redistribution: prefix -> (entry, target areas)
        self._redistributed: Dict[
            IpPrefix, Tuple[PrefixEntry, Tuple[str, ...]]
        ] = {}
        self._advertised_keys: set = set()  # {(area, key)}
        if prefix_updates_queue is not None:
            self.evb.add_queue_reader(
                prefix_updates_queue.get_reader(f"pm:{my_node_name}"),
                self._on_event,
            )
        if decision_route_updates_queue is not None:
            self.evb.add_queue_reader(
                decision_route_updates_queue.get_reader(
                    f"pm-redist:{my_node_name}"
                ),
                self._on_route_update,
            )

    def start(self) -> None:
        self.evb.run_in_thread()

    def stop(self) -> None:
        self.evb.stop()
        self.evb.join()

    # -- queue interface --------------------------------------------------

    def _on_event(self, event: PrefixEvent) -> None:
        if event.event_type == PrefixEventType.ADD_PREFIXES:
            self._advertise(event.prefixes)
        elif event.event_type == PrefixEventType.WITHDRAW_PREFIXES:
            self._withdraw([e.prefix for e in event.prefixes])
        elif event.event_type == PrefixEventType.SYNC_PREFIXES_BY_TYPE:
            assert event.type is not None
            self._sync_by_type(event.type, event.prefixes)
        elif event.event_type == PrefixEventType.WITHDRAW_PREFIXES_BY_TYPE:
            assert event.type is not None
            self._withdraw(
                [
                    p
                    for (t, p) in list(self._prefixes)
                    if t == event.type
                ]
            )

    def _on_route_update(self, update) -> None:
        """Re-originate Decision's best routes into other areas
        (reference: PrefixManager's decisionRouteUpdatesQueue consumer)."""
        changed = False
        own_prefixes = {
            p for (t, p) in self._prefixes if t != PrefixType.RIB
        }
        for prefix, entry in getattr(
            update, "unicast_routes_to_update", {}
        ).items():
            best = entry.best_prefix_entry
            if best is None or prefix in own_prefixes:
                # a prefix we originate ourselves is never redistributed;
                # drop any redistribution recorded before it became ours
                changed |= self._redistributed.pop(prefix, None) is not None
                continue
            new_stack = tuple(best.area_stack)
            if entry.best_area and entry.best_area not in new_stack:
                new_stack = new_stack + (entry.best_area,)
            targets = tuple(a for a in self._areas if a not in new_stack)
            if not targets:
                changed |= self._redistributed.pop(prefix, None) is not None
                continue
            redist = PrefixEntry(
                prefix=prefix,
                type=PrefixType.RIB,
                forwarding_type=best.forwarding_type,
                forwarding_algorithm=best.forwarding_algorithm,
                min_nexthop=best.min_nexthop,
                # bump distance so the re-originated copy always loses
                # best-route selection to the original — without this,
                # two border routers' identical-metric copies can tie
                # with the source and oscillate advertise/withdraw
                metrics=replace(
                    best.metrics, distance=best.metrics.distance + 1
                ),
                tags=best.tags,
                area_stack=new_stack,
            )
            if self._redistributed.get(prefix) != (redist, targets):
                self._redistributed[prefix] = (redist, targets)
                changed = True
        for prefix in getattr(update, "unicast_routes_to_delete", []):
            changed |= self._redistributed.pop(prefix, None) is not None
        if changed:
            self._update_kvstore()

    # -- public API (thread-safe) -----------------------------------------

    def advertise_prefixes(self, entries: List[PrefixEntry]) -> None:
        self.evb.call_and_wait(lambda: self._advertise(entries))

    def withdraw_prefixes(self, prefixes: List[IpPrefix]) -> None:
        self.evb.call_and_wait(lambda: self._withdraw(prefixes))

    def sync_prefixes_by_type(
        self, prefix_type: PrefixType, entries: List[PrefixEntry]
    ) -> None:
        self.evb.call_and_wait(lambda: self._sync_by_type(prefix_type, entries))

    def get_prefixes(self) -> List[PrefixEntry]:
        return self.evb.call_and_wait(
            lambda: sorted(self._prefixes.values(), key=lambda e: e.prefix)
        )

    def get_redistributed(self) -> Dict[IpPrefix, Tuple[PrefixEntry, Tuple[str, ...]]]:
        """Cross-area re-originated routes (entry, target areas)."""
        return self.evb.call_and_wait(lambda: dict(self._redistributed))

    # -- internals --------------------------------------------------------

    def _record_own(self, entry: PrefixEntry) -> None:
        """Record one own advertisement (shared by advertise + sync)."""
        if entry.metrics == PrefixMetrics():
            # origination default (reference: buildOriginatedPrefixDb)
            entry = replace(
                entry,
                metrics=PrefixMetrics(
                    path_preference=DEFAULT_PATH_PREFERENCE,
                    source_preference=DEFAULT_SOURCE_PREFERENCE,
                ),
            )
        self._prefixes[(entry.type, entry.prefix)] = entry
        if entry.type != PrefixType.RIB:
            # an own advertisement supersedes any cross-area
            # redistribution of the same prefix
            self._redistributed.pop(entry.prefix, None)

    def _advertise(self, entries: List[PrefixEntry]) -> None:
        """reference: PrefixManager.cpp advertisePrefixesImpl."""
        for entry in entries:
            self._record_own(entry)
        self._update_kvstore()

    def _withdraw(self, prefixes: List[IpPrefix]) -> None:
        for key in [k for k in self._prefixes if k[1] in set(prefixes)]:
            del self._prefixes[key]
        self._update_kvstore()

    def _sync_by_type(
        self, prefix_type: PrefixType, entries: List[PrefixEntry]
    ) -> None:
        for key in [k for k in self._prefixes if k[0] == prefix_type]:
            del self._prefixes[key]
        for entry in entries:
            self._record_own(replace(entry, type=prefix_type))
        self._update_kvstore()

    def _best_own_entries(self) -> Dict[IpPrefix, PrefixEntry]:
        """One advertisement per prefix: the best-metrics entry among the
        types advertising it, deterministic tie-break by lowest type
        (reference: PrefixManager.cpp:346-348 syncKvStore picks
        selectBestPrefixMetrics across the per-type entries)."""
        best: Dict[IpPrefix, Tuple[tuple, PrefixEntry]] = {}
        for (ptype, prefix), entry in self._prefixes.items():
            rank = (entry.metrics.comparison_key(), -int(ptype))
            cur = best.get(prefix)
            if cur is None or rank > cur[0]:
                best[prefix] = (rank, entry)
        return {p: e for p, (_, e) in best.items()}

    def _update_kvstore(self) -> None:
        # (area, key) -> payload; keys repeat across areas in full-db mode
        wanted: Dict[Tuple[str, str], bytes] = {}
        own = self._best_own_entries()
        for area in self._areas:
            redist = {
                p: e
                for p, (e, targets) in self._redistributed.items()
                if area in targets and p not in own
            }
            if self._per_prefix_keys:
                for prefix, entry in {**own, **redist}.items():
                    key = keyutil.per_prefix_key(
                        self.my_node_name, area, prefix
                    )
                    db = PrefixDatabase(
                        this_node_name=self.my_node_name,
                        prefix_entries=(entry,),
                        area=area,
                    )
                    wanted[(area, key)] = wire.dumps(db)
            else:
                key = keyutil.prefix_db_key(self.my_node_name)
                db = PrefixDatabase(
                    this_node_name=self.my_node_name,
                    prefix_entries=tuple(
                        e
                        for _, e in sorted(
                            {**own, **redist}.items(),
                            key=lambda kv: kv[0],
                        )
                    ),
                    area=area,
                )
                wanted[(area, key)] = wire.dumps(db)

        # withdraw keys that are no longer advertised: flood the delete
        # marker so other Decisions drop the entries
        for area, key in list(self._advertised_keys):
            if (area, key) not in wanted:
                parsed = keyutil.parse_per_prefix_key(key)
                delete_db = PrefixDatabase(
                    this_node_name=self.my_node_name,
                    prefix_entries=(
                        (PrefixEntry(prefix=parsed[2]),) if parsed else ()
                    ),
                    delete_prefix=True,
                    area=area,
                )
                self._client.clear_key(
                    area,
                    key,
                    wire.dumps(delete_db),
                    ttl=KVSTORE_TOMBSTONE_TTL_MS,
                )
                self._advertised_keys.discard((area, key))

        for (area, key), payload in wanted.items():
            self._client.persist_key(area, key, payload)
            self._advertised_keys.add((area, key))
