"""openr-tpu: a TPU-native link-state routing framework.

A ground-up re-design of the capabilities of Open/R (reference:
/root/reference, Facebook's link-state IGP) around JAX/XLA on TPU:

- ``openr_tpu.types``     -- the typed message schema (reference: openr/if/*.thrift)
- ``openr_tpu.graph``     -- host LinkState graph + device snapshot compiler
                             (reference: openr/decision/LinkState.{h,cpp})
- ``openr_tpu.ops``       -- batched all-sources SPF + route-selection kernels
- ``openr_tpu.parallel``  -- device-mesh sharding of the source dimension
- ``openr_tpu.decision``  -- SpfSolver / Decision module
                             (reference: openr/decision/Decision.cpp)
- ``openr_tpu.kvstore``   -- flooded, eventually-consistent LSDB
                             (reference: openr/kvstore/KvStore.cpp)
- ``openr_tpu.messaging`` -- typed replicated queues (reference: openr/messaging)
- ``openr_tpu.spark``     -- neighbor discovery (reference: openr/spark)
- ``openr_tpu.linkmonitor``, ``openr_tpu.fib``, ``openr_tpu.prefixmgr``,
  ``openr_tpu.ctrl``, ``openr_tpu.cli`` -- the protocol/daemon shell.

The compute hot path (all-sources shortest paths, ECMP next-hop derivation,
best-route selection) runs as jitted JAX kernels over dense int32 metric
arrays resident in HBM; the protocol machinery is host-side Python/C++ with
the same module-per-thread, typed-queue dataflow as the reference daemon.
"""

__version__ = "0.3.0"
