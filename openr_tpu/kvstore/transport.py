"""KvStore cross-process peer transport over wire-RPC.

The analogue of the reference's thrift ``KvStoreService`` peer channel
(and the legacy fbzmq ROUTER socket it dual-stacks with; reference:
KvStore.cpp:2940-2973): exposes ``getKvStoreKeyValsFiltered`` and
``setKvStoreKeyVals`` for remote stores, so daemons on different hosts
flood and full-sync over TCP (default port 60002,
reference: Constants.h:257).
"""

from __future__ import annotations

from typing import Optional

from typing import List

from openr_tpu.dual.dual import DualMessage
from openr_tpu.kvstore.store import KvStore, PeerTransport
from openr_tpu.types import KeyDumpParams, KeySetParams, Publication
from openr_tpu.utils.rpc import RpcClient, RpcServer

KVSTORE_RPC_PORT = 60002


class KvStorePeerServer:
    """Expose a KvStore to remote peers."""

    def __init__(self, kvstore: KvStore, host: str = "::", port: int = 0,
                 listen: bool = True):
        self._kvstore = kvstore
        # "::" binds dual-stack v6 (RpcServer picks AF_INET6 for v6
        # hosts) — neighbors dial fe80:: link-local transports, which a
        # v4-only listener can never accept
        self._server = RpcServer(host=host, port=port, listen=listen)
        self._server.register(
            "getKvStoreKeyValsFiltered",
            self._get_filtered,
            arg_types=[str, KeyDumpParams],
            result_type=Publication,
        )
        self._server.register(
            "setKvStoreKeyVals",
            self._set_key_vals,
            arg_types=[str, KeySetParams],
            result_type=type(None),
        )
        self._server.register(
            "processKvStoreDualMessage",
            self._process_dual,
            arg_types=[str, str, List[DualMessage]],
            result_type=type(None),
        )
        self._server.register(
            "updateFloodTopologyChild",
            self._kvstore.set_flood_topo_child,
            arg_types=[str, str, str, bool],
            result_type=type(None),
        )
        self.port = self._server.port

    def _get_filtered(self, area: str, params: KeyDumpParams) -> Publication:
        return self._kvstore.dump_with_filters(area, params)

    def _set_key_vals(self, area: str, params: KeySetParams) -> None:
        self._kvstore.set_key_vals(
            area, params, sender_id=params.originator_id
        )

    def _process_dual(
        self, area: str, sender: str, msgs: List[DualMessage]
    ) -> None:
        self._kvstore.process_dual_messages(area, sender, msgs)

    def serve_connection(self, sock) -> None:
        self._server.serve_connection(sock)

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop()


class TcpPeerTransport(PeerTransport):
    """Dial a remote KvStorePeerServer (the thrift peer-client analogue,
    reference: KvStore.cpp:1400 requestThriftPeerSync client path)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self._client = RpcClient(host, port, timeout_s=timeout_s)
        self.endpoint = (host, port)

    def get_key_vals_filtered(
        self, area: str, params: KeyDumpParams
    ) -> Publication:
        return self._client.call(
            "getKvStoreKeyValsFiltered", [area, params], Publication
        )

    def set_key_vals(self, area: str, params: KeySetParams) -> None:
        self._client.call("setKvStoreKeyVals", [area, params], type(None))

    def send_dual_messages(self, area: str, sender_id: str, msgs) -> None:
        self._client.call(
            "processKvStoreDualMessage",
            [area, sender_id, list(msgs)],
            type(None),
        )

    def set_flood_topo_child(
        self, area: str, root_id: str, child_id: str, is_set: bool
    ) -> None:
        self._client.call(
            "updateFloodTopologyChild",
            [area, root_id, child_id, is_set],
            type(None),
        )

    def close(self) -> None:
        self._client.close()
