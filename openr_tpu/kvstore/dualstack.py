"""Dual-stack KvStore peer server: both wires on ONE advertised port.

The reference runs its legacy and thrift peer transports
simultaneously during wire migrations (KvStore.cpp:2940-2973 branches
per peer). Here both wire formats are framed ``[u32 length][payload]``
and the first payload byte disambiguates them unambiguously:

- thrift CompactProtocol messages begin with the protocol id ``0x82``;
- the framework RPC payload begins with its blob count, a small
  integer that can never be 0x82 (requests carry a method name plus
  arguments — single-digit blob counts).

One listener peeks the first frame's leading bytes and then runs the
matching backend's request loop DIRECTLY on the accepted socket (no
loopback splice, no extra copies): both backend servers expose
``serve_connection`` for exactly this. A daemon advertises one
kvStoreCmdPort (Spark handshake) and peers dial it with whichever wire
they speak.
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time
from typing import Optional

from openr_tpu.kvstore.store import KvStore
from openr_tpu.kvstore.thrift_peer import KvStoreThriftPeerServer
from openr_tpu.kvstore.transport import KvStorePeerServer
from openr_tpu.utils.rpc import apply_bind_family
from openr_tpu.utils.thrift_rpc import PROTOCOL_ID

_SNIFF_BYTES = 5  # u32 frame length + first payload byte
_SNIFF_DEADLINE_S = 30.0


def _peek_first_bytes(sock: socket.socket) -> Optional[bytes]:
    """Wait until the first frame header + payload byte are buffered.
    MSG_PEEK returns whatever has ARRIVED — clients that write the
    frame header and payload in separate sends (several stock thrift
    transports do) need more than one peek."""
    deadline = time.monotonic() + _SNIFF_DEADLINE_S
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return None
        sock.settimeout(remaining)
        try:
            head = sock.recv(_SNIFF_BYTES, socket.MSG_PEEK)
        except OSError:
            return None
        if not head:
            return None  # peer hung up
        if len(head) >= _SNIFF_BYTES:
            return head
        # partial arrival: yield briefly rather than hot-spinning on
        # MSG_PEEK (which does not consume and so returns immediately)
        time.sleep(0.005)


class DualStackPeerServer:
    """One listening port serving both KvStore peer wires."""

    def __init__(self, kvstore: KvStore, host: str = "0.0.0.0",
                 port: int = 0):
        # backends are used for their serve_connection dispatch loops;
        # their own loopback ephemeral listeners also run (idle,
        # unadvertised) because socketserver.shutdown() deadlocks on a
        # server whose serve_forever never ran — starting them is the
        # cheap way to keep stop() safe
        self._rpc_backend = KvStorePeerServer(kvstore, host="127.0.0.1")
        self._thrift_backend = KvStoreThriftPeerServer(
            kvstore, host="127.0.0.1"
        )
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                sock = self.request
                head = _peek_first_bytes(sock)
                if head is None:
                    return
                sock.settimeout(None)
                if head[4] == PROTOCOL_ID:
                    outer._thrift_backend.serve_connection(sock)
                else:
                    outer._rpc_backend.serve_connection(sock)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        apply_bind_family(Server, host)
        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._rpc_backend.start()
        self._thrift_backend.start()
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="kvstore-dualstack",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._thrift_backend.stop()
        self._rpc_backend.stop()
