"""Dual-stack KvStore peer server: both wires on ONE advertised port.

The reference runs its legacy and thrift peer transports
simultaneously during wire migrations (KvStore.cpp:2940-2973 branches
per peer). Here all wire formats are framed ``[u32 length][payload]``
and the leading payload bytes disambiguate them unambiguously:

- thrift CompactProtocol messages begin with the protocol id ``0x82``;
- THeader-wrapped thrift (the fbthrift client default) begins with the
  TWO-byte magic ``0x0FFF`` (both bytes are checked: 0x0F alone would
  collide with a 15-argument framework RPC one day, but 0x0F followed
  by 0xFF cannot be a framework frame — the second byte there is the
  top byte of a u32 blob length bounded far below 0xFF000000);
- bare framed strict-BinaryProtocol thrift begins with the TWO-byte
  version word ``0x8001`` (same two-byte argument: a framework frame
  leading with blob count 0x80 would need 128 arguments, and its
  second byte could not be 0x01 — the blob-length top byte);
- the framework RPC payload begins with its blob count, a small
  integer that can never be 0x82.

The shared predicate lives in ``utils.thrift_rpc.is_thrift_head`` —
every demultiplexer (here and ctrl/server.py) classifies through it.

One listener peeks the first frame's leading bytes and then runs the
matching backend's request loop DIRECTLY on the accepted socket (no
loopback splice, no extra copies): both backend servers expose
``serve_connection`` for exactly this. A daemon advertises one
kvStoreCmdPort (Spark handshake) and peers dial it with whichever wire
they speak.
"""

from __future__ import annotations

import socket
import socketserver
import threading
from typing import Optional

from openr_tpu.kvstore.store import KvStore
from openr_tpu.kvstore.thrift_peer import KvStoreThriftPeerServer
from openr_tpu.kvstore.transport import KvStorePeerServer
from openr_tpu.utils.rpc import apply_bind_family, peek_first_bytes
from openr_tpu.utils.thrift_rpc import is_thrift_head

_SNIFF_BYTES = 6  # u32 frame length + two payload bytes


def _peek_first_bytes(sock: socket.socket) -> Optional[bytes]:
    return peek_first_bytes(sock, _SNIFF_BYTES)


class DualStackPeerServer:
    """One listening port serving both KvStore peer wires."""

    def __init__(self, kvstore: KvStore, host: str = "0.0.0.0",
                 port: int = 0):
        # backends are pure dispatchers: no sockets of their own, the
        # demux below owns the one advertised port
        self._rpc_backend = KvStorePeerServer(kvstore, listen=False)
        self._thrift_backend = KvStoreThriftPeerServer(
            kvstore, listen=False
        )
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                sock = self.request
                head = _peek_first_bytes(sock)
                if head is None:
                    return
                sock.settimeout(None)
                if is_thrift_head(head):
                    # any thrift wire — bare compact, THeader-wrapped,
                    # bare binary — lands on the thrift backend, which
                    # mirrors the request's wrapping and protocol
                    outer._thrift_backend.serve_connection(sock)
                else:
                    outer._rpc_backend.serve_connection(sock)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        apply_bind_family(Server, host)
        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._rpc_backend.start()
        self._thrift_backend.start()
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="kvstore-dualstack",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._thrift_backend.stop()
        self._rpc_backend.stop()
