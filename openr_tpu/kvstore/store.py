"""KvStore: the flooded, eventually-consistent link-state database.

Behavioral parity with the reference ``openr/kvstore/KvStore.{h,cpp}``:

- CRDT-style merge ordered by (version, originatorId, value bytes), with
  TTL-only updates on (version, originator) match and higher ttlVersion
  (reference: KvStore.cpp:263 mergeKeyValues, :426 compareValues)
- per-area stores (one ``KvStoreDb`` per area, reference: KvStore.h:202)
- flood-on-merge to all INITIALIZED peers except the sender; merge no-ops
  stop the flood (loop suppression; reference: KvStore.cpp:2861
  floodPublication, peer gating :2957)
- 3-way initial full sync: initiator sends its hash dump, responder
  returns better/missing values plus the key list the initiator should
  push back (reference: dumpDifference :1351, finalizeFullSync :2727),
  with the per-peer IDLE -> SYNCING -> INITIALIZED FSM and exponential
  backoff on failure (reference: KvStore.h:46-61)
- TTL countdown and local expiry flood (reference: cleanupTtlCountdownQueue
  :2611)

Transport is abstracted behind ``PeerTransport`` (the reference dual-stacks
fbzmq ROUTER and thrift; here: an in-process transport for tests/daemons in
one process and a TCP transport for real deployments). Peer I/O runs on an
executor so store event loops never block on each other.
"""

from __future__ import annotations

import heapq
import logging
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from openr_tpu.faults.injector import fault_point, register_fault_site
from openr_tpu.monitor.monitor import push_log_sample
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.telemetry import get_registry, get_tracer
from openr_tpu.types import (
    DEFAULT_AREA,
    TTL_INFINITY,
    KeyDumpParams,
    KeySetParams,
    KvStorePeerState,
    Publication,
    Value,
)
from openr_tpu.utils import wire
from openr_tpu.utils.eventbase import ExponentialBackoff, OpenrEventBase

# ttl decrement applied when re-flooding, so a key eventually dies even in
# a flood loop (reference: Constants.h kTtlDecrement)
TTL_DECREMENT_MS = 1

_LOG = logging.getLogger(__name__)

# injection seams for the store's two peer-I/O paths: the 3-way full
# sync request and the flood fan-out. Both fire on the executor thread
# inside the existing try blocks, so an injected fault takes the same
# backoff + re-sync recovery path as a real transport error.
FAULT_KV_FULL_SYNC = register_fault_site("kvstore.full_sync")
FAULT_KV_FLOOD = register_fault_site("kvstore.flood")


@dataclass
class KvStoreFilters:
    """Key prefix + originator filter (reference: KvStoreFilters in
    KvStore.h; OR semantics across the two dimensions)."""

    key_prefixes: List[str] = field(default_factory=list)
    originator_ids: Set[str] = field(default_factory=set)

    def key_match(self, key: str, value: Value) -> bool:
        if not self.key_prefixes and not self.originator_ids:
            return True
        if self.key_prefixes and any(key.startswith(p) for p in self.key_prefixes):
            return True
        if self.originator_ids and value.originator_id in self.originator_ids:
            return True
        return False


def compare_values(v1: Value, v2: Value) -> int:
    """1 if v1 better, -1 if v2 better, 0 equal, -2 unknown.
    reference: KvStore.cpp:426 compareValues."""
    if v1.version != v2.version:
        return 1 if v1.version > v2.version else -1
    if v1.originator_id != v2.originator_id:
        return 1 if v1.originator_id > v2.originator_id else -1
    if v1.hash is not None and v2.hash is not None and v1.hash == v2.hash:
        if v1.ttl_version != v2.ttl_version:
            return 1 if v1.ttl_version > v2.ttl_version else -1
        return 0
    if v1.value is not None and v2.value is not None:
        if v1.value == v2.value:
            if v1.ttl_version != v2.ttl_version:
                return 1 if v1.ttl_version > v2.ttl_version else -1
            return 0
        return 1 if v1.value > v2.value else -1
    return -2


def merge_key_values(
    store: Dict[str, Value],
    key_vals: Dict[str, Value],
    filters: Optional[KvStoreFilters] = None,
) -> Dict[str, Value]:
    """Merge key_vals into store; returns the accepted updates (what must
    be flooded onward). reference: KvStore.cpp:263 mergeKeyValues."""
    updates: Dict[str, Value] = {}
    for key, value in key_vals.items():
        if filters is not None and not filters.key_match(key, value):
            continue
        # TTL must be infinite or positive
        if value.ttl != TTL_INFINITY and value.ttl <= 0:
            continue
        existing = store.get(key)
        my_version = existing.version if existing is not None else 0
        if value.version < my_version:
            continue

        update_all = False
        update_ttl = False
        if value.value is not None:
            if value.version > my_version:
                update_all = True
            elif value.originator_id > existing.originator_id:
                update_all = True
            elif value.originator_id == existing.originator_id:
                if existing.value is None or value.value > existing.value:
                    update_all = True
                elif value.value == existing.value:
                    if value.ttl_version > existing.ttl_version:
                        update_ttl = True
        if (
            value.value is None
            and existing is not None
            and value.version == existing.version
            and value.originator_id == existing.originator_id
            and value.ttl_version > existing.ttl_version
        ):
            update_ttl = True

        if not update_all and not update_ttl:
            continue

        if update_all:
            new_value = Value(
                version=value.version,
                originator_id=value.originator_id,
                value=value.value,
                ttl=value.ttl,
                ttl_version=value.ttl_version,
                hash=value.hash
                if value.hash is not None
                else wire.generate_hash(
                    value.version, value.originator_id, value.value
                ),
            )
            store[key] = new_value
        else:  # ttl-only refresh
            existing.ttl = value.ttl
            existing.ttl_version = value.ttl_version
        updates[key] = value
    return updates


class PeerTransport:
    """RPC surface a store exposes to its peers (reference: the
    KvStoreService thrift interface / fbzmq ROUTER socket)."""

    # (host, port) the transport dials, when it dials anywhere — the
    # ctrl surface reports it as the thrift PeerSpec peerAddr/ctrlPort
    # (reference: openr/if/KvStore.thrift PeerSpec). In-process
    # transports have no endpoint.
    endpoint: Optional[Tuple[str, int]] = None

    def get_key_vals_filtered(
        self, area: str, params: KeyDumpParams
    ) -> Publication:
        raise NotImplementedError

    def set_key_vals(self, area: str, params: KeySetParams) -> None:
        raise NotImplementedError

    def send_dual_messages(self, area: str, sender_id: str, msgs) -> None:
        """Deliver DUAL messages for the flood-topology computation
        (reference: KvStoreService processKvStoreDualMessage)."""
        raise NotImplementedError

    def set_flood_topo_child(
        self, area: str, root_id: str, child_id: str, is_set: bool
    ) -> None:
        """Register/unregister the sender as an SPT child of this store
        for the given flood root (reference: KvStoreService
        updateFloodTopologyChild / FloodTopoSetParams)."""
        raise NotImplementedError


class InProcessTransport(PeerTransport):
    """Directly call into another KvStore in the same process (used by
    tests and single-process multi-node simulations; the analogue of the
    reference's KvStoreWrapper-linked stores)."""

    def __init__(self, target: "KvStore"):
        self._target = target

    def get_key_vals_filtered(
        self, area: str, params: KeyDumpParams
    ) -> Publication:
        return self._target.dump_with_filters(area, params)

    def set_key_vals(self, area: str, params: KeySetParams) -> None:
        self._target.set_key_vals(area, params, sender_id=params.originator_id)

    def send_dual_messages(self, area: str, sender_id: str, msgs) -> None:
        self._target.process_dual_messages(area, sender_id, msgs)

    def set_flood_topo_child(
        self, area: str, root_id: str, child_id: str, is_set: bool
    ) -> None:
        self._target.set_flood_topo_child(area, root_id, child_id, is_set)


@dataclass
class _Peer:
    name: str
    transport: PeerTransport
    state: KvStorePeerState = KvStorePeerState.IDLE
    backoff: ExponentialBackoff = field(
        default_factory=lambda: ExponentialBackoff(0.05, 5.0)
    )
    # floods that arrived while this peer was mid-full-sync; flushed when
    # it reaches INITIALIZED (otherwise an update racing the full sync
    # would be lost until the next anti-entropy pass)
    pending_flood: Dict[str, Value] = field(default_factory=dict)
    # monotonic stamp of the in-flight full sync (event-log duration)
    sync_started: Optional[float] = None


class KvStoreDb:
    """One area's store. All mutation happens on the owning KvStore's
    event base thread."""

    def __init__(
        self,
        area: str,
        node_id: str,
        evb: OpenrEventBase,
        updates_queue: ReplicateQueue,
        executor: ThreadPoolExecutor,
        filters: Optional[KvStoreFilters] = None,
        enable_flood_optimization: bool = False,
        is_flood_root: bool = False,
        flood_rate: Optional[Tuple[float, int]] = None,
        log_sample_queue: Optional[ReplicateQueue] = None,
        merge_hook=None,
    ):
        self.area = area
        self.node_id = node_id
        self._evb = evb
        self._updates_queue = updates_queue
        self._executor = executor
        self._filters = filters
        self._log_sample_queue = log_sample_queue
        # crash-safe state plane: called with (area, accepted updates)
        # after every merge, on this evb (StatePlane.on_kvstore_merge)
        self._merge_hook = merge_hook
        self.key_vals: Dict[str, Value] = {}
        self.peers: Dict[str, _Peer] = {}
        # flood rate limiting: token bucket + coalescing buffer
        # (reference: KvStore.cpp:1129 floodLimiter_ BasicTokenBucket +
        # bufferPublication/floodBufferedUpdates)
        self._flood_rate = flood_rate
        self._flood_tokens = float(flood_rate[1]) if flood_rate else 0.0
        self._flood_refill_t = time.monotonic()
        self._flood_buffer: Set[str] = set()
        self._flood_timer = None
        # DUAL-computed SPT flood topology (reference: KvStoreDb inherits
        # DualNode; flood-optimization flag KvStore.cpp:2940-2973). Off by
        # default, matching the reference.
        self.dual = None
        if enable_flood_optimization:
            from openr_tpu.dual.dual import DualNode

            self.dual = DualNode(
                node_id,
                is_root=is_flood_root,
                nexthop_change_cb=self._on_dual_nexthop_change,
            )
        # (expiry_monotonic, key, version, originator, ttl_version)
        self._ttl_heap: List[Tuple[float, str, int, str, int]] = []
        self._ttl_timer = None
        self.counters: Dict[str, int] = {
            "kvstore.received_key_vals": 0,
            "kvstore.updated_key_vals": 0,
            "kvstore.expired_keys": 0,
            "kvstore.full_sync_count": 0,
            "kvstore.flood_count": 0,
            "kvstore.spt_floods": 0,
            "kvstore.rate_limit_suppress": 0,
            "kvstore.full_sync_failures": 0,
            "kvstore.flood_errors": 0,
            "kvstore.journal_errors": 0,
        }

    def _log_sample(self, **fields) -> None:
        """reference: KvStore.cpp:3104 logSyncEvent / :3118 logKvEvent."""
        push_log_sample(
            self._log_sample_queue,
            node_name=self.node_id,
            area=self.area,
            **fields,
        )

    # -- merge + flood ----------------------------------------------------

    def set_key_vals(
        self, params: KeySetParams, sender_id: Optional[str] = None
    ) -> None:
        self.counters["kvstore.received_key_vals"] += len(params.key_vals)
        updates = merge_key_values(self.key_vals, params.key_vals, self._filters)
        self.counters["kvstore.updated_key_vals"] += len(updates)
        if not updates:
            return
        self._track_ttls(updates)
        if self._merge_hook is not None:
            # write-ahead: the journal lands before the publication so a
            # crash mid-publish replays at least what Decision consumed
            try:
                self._merge_hook(self.area, updates)
            except Exception as exc:  # noqa: BLE001 - journal must not kill the merge path
                self.counters["kvstore.journal_errors"] += 1
                get_registry().counter_bump("state.journal_errors")
                _LOG.error(
                    "kvstore[%s] state-plane journal append failed: %s",
                    self.area, exc,
                )
        # telemetry: every accepted merge births one trace; Decision
        # adopts the oldest trace in a debounce window, Fib retires it
        trace = get_tracer().start(
            "kvstore.publish",
            node=self.node_id,
            area=self.area,
            keys=sorted(updates)[:8],
            n_keys=len(updates),
        )
        self._publish(
            Publication(
                key_vals=dict(updates), area=self.area, trace=trace
            )
        )
        self._flood(updates, exclude=sender_id)

    def _publish(self, pub: Publication) -> None:
        self._updates_queue.push(pub)

    # -- flood rate limiting ---------------------------------------------

    def _flood_consume(self) -> bool:
        """Take one token from the flood bucket (refilled at
        flood_msg_per_sec up to the burst size)."""
        rate, burst = self._flood_rate
        now = time.monotonic()
        self._flood_tokens = min(
            float(burst),
            self._flood_tokens + (now - self._flood_refill_t) * rate,
        )
        self._flood_refill_t = now
        if self._flood_tokens >= 1.0:
            self._flood_tokens -= 1.0
            return True
        return False

    def _schedule_buffered_flood(self) -> None:
        if self._flood_timer is not None:
            return
        # reference: Constants.h:189 kFloodPendingPublication = 100ms
        self._flood_timer = self._evb.schedule_timeout(
            0.1, self._flood_buffered
        )

    def _flood_buffered(self) -> None:
        """Re-flood the coalesced buffer with the CURRENT stored values
        (reference: floodBufferedUpdates — keys are merged, so a burst of
        N updates to one key floods once)."""
        self._flood_timer = None
        if not self._flood_buffer:
            return
        if not self._flood_consume():
            self._schedule_buffered_flood()
            return
        updates = {
            key: self.key_vals[key]
            for key in self._flood_buffer
            if key in self.key_vals
        }
        self._flood_buffer.clear()
        if updates:
            self._flood_now(updates, exclude=None)

    def _flood(self, updates: Dict[str, Value], exclude: Optional[str]) -> None:
        if self._flood_rate is not None:
            if not self._flood_consume():
                # suppressed: coalesce into the buffer, retry on a timer
                self.counters["kvstore.rate_limit_suppress"] += 1
                self._flood_buffer.update(updates)
                self._schedule_buffered_flood()
                return
            if self._flood_buffer:
                # token in hand and older keys pending: merge and flood
                # the whole buffer at once so ordering is preserved
                # (reference: floodPublication's buffer-merge path)
                self._flood_buffer.update(updates)
                updates = {
                    key: self.key_vals[key]
                    for key in self._flood_buffer
                    if key in self.key_vals
                }
                self._flood_buffer.clear()
                if self._flood_timer is not None:
                    self._flood_timer.cancel()
                    self._flood_timer = None
                exclude = None  # forwarded batch: no single sender
                if not updates:
                    return
        self._flood_now(updates, exclude)

    def _flood_now(
        self, updates: Dict[str, Value], exclude: Optional[str]
    ) -> None:
        """Flood accepted updates to every INITIALIZED peer except the one
        we learned them from. With flood optimization on and a converged
        SPT, only the SPT links (parent + children of the elected flood
        root) carry the flood (reference: KvStore.cpp:2957 floodPeers =
        getFloodPeers(rootId))."""
        flooded = self._decrement_ttls(updates)
        if not flooded:
            return
        spt_targets = None
        if self.dual is not None:
            root = self.dual.pick_flood_root()
            if root is not None:
                spt = self.dual.spt_peers(root)
                if spt:
                    spt_targets = spt
                    self.counters["kvstore.spt_floods"] += 1
        for peer in list(self.peers.values()):
            if peer.name == exclude:
                continue
            if peer.state == KvStorePeerState.SYNCING:
                # a syncing peer accumulates floods regardless of SPT:
                # its full sync raced this update and would miss it
                peer.pending_flood.update(flooded)
                continue
            if peer.state != KvStorePeerState.INITIALIZED:
                continue
            if spt_targets is not None and peer.name not in spt_targets:
                continue
            self.counters["kvstore.flood_count"] += 1
            params = KeySetParams(
                key_vals=dict(flooded),
                originator_id=self.node_id,
                solicit_response=False,
            )

            def flood_one(t=peer.transport, p=params) -> None:
                fault_point(FAULT_KV_FLOOD)
                t.set_key_vals(self.area, p)

            self._async_peer_call(peer, flood_one)

    def _decrement_ttls(self, updates: Dict[str, Value]) -> Dict[str, Value]:
        out: Dict[str, Value] = {}
        for key, value in updates.items():
            if value.ttl == TTL_INFINITY:
                out[key] = value
                continue
            remaining = value.ttl - TTL_DECREMENT_MS
            if remaining <= 0:
                continue
            out[key] = Value(
                version=value.version,
                originator_id=value.originator_id,
                value=value.value,
                ttl=remaining,
                ttl_version=value.ttl_version,
                hash=value.hash,
            )
        return out

    # -- TTL countdown ----------------------------------------------------

    def _track_ttls(self, updates: Dict[str, Value]) -> None:
        now = time.monotonic()
        for key, value in updates.items():
            stored = self.key_vals.get(key)
            if stored is None or stored.ttl == TTL_INFINITY:
                continue
            heapq.heappush(
                self._ttl_heap,
                (
                    now + stored.ttl / 1000.0,
                    key,
                    stored.version,
                    stored.originator_id,
                    stored.ttl_version,
                ),
            )
        self._schedule_ttl_cleanup()

    def _schedule_ttl_cleanup(self) -> None:
        if not self._ttl_heap:
            return
        if self._ttl_timer is not None:
            self._ttl_timer.cancel()
        delay = max(0.0, self._ttl_heap[0][0] - time.monotonic())
        self._ttl_timer = self._evb.schedule_timeout(delay, self._cleanup_ttls)

    def _cleanup_ttls(self) -> None:
        """Expire keys whose countdown entry still matches the stored value
        (reference: KvStore.cpp:2611 cleanupTtlCountdownQueue)."""
        self._ttl_timer = None
        now = time.monotonic()
        expired: List[str] = []
        while self._ttl_heap and self._ttl_heap[0][0] <= now:
            _, key, version, originator, ttl_version = heapq.heappop(
                self._ttl_heap
            )
            stored = self.key_vals.get(key)
            if (
                stored is not None
                and stored.version == version
                and stored.originator_id == originator
                and stored.ttl_version == ttl_version
                and stored.ttl != TTL_INFINITY
            ):
                del self.key_vals[key]
                expired.append(key)
        if expired:
            self.counters["kvstore.expired_keys"] += len(expired)
            for key in expired:
                self._log_sample(event="KEY_EXPIRE", key=key)
            self._publish(Publication(expired_keys=expired, area=self.area))
        self._schedule_ttl_cleanup()

    # -- dumps ------------------------------------------------------------

    def dump_with_filters(self, params: KeyDumpParams) -> Publication:
        """Full dump, or hash-differential dump when key_val_hashes given
        (the responder side of the 3-way sync)."""
        filters = KvStoreFilters(
            key_prefixes=[params.prefix] if params.prefix else [],
            originator_ids=set(params.originator_ids),
        )
        matching = {
            k: v for k, v in self.key_vals.items() if filters.key_match(k, v)
        }
        if params.keys:
            matching = {k: v for k, v in matching.items() if k in params.keys}
        if params.key_val_hashes is not None:
            return self._dump_difference(matching, params.key_val_hashes)
        return Publication(
            key_vals=self._update_publication_ttl(matching), area=self.area
        )

    def dump_hashes(self, prefix: str = "") -> Publication:
        """Hash-only dump (reference: KvStore.cpp:1327 dumpHashWithFilters)."""
        out: Dict[str, Value] = {}
        for key, v in self.key_vals.items():
            if prefix and not key.startswith(prefix):
                continue
            out[key] = Value(
                version=v.version,
                originator_id=v.originator_id,
                value=None,
                ttl=v.ttl,
                ttl_version=v.ttl_version,
                hash=v.hash,
            )
        return Publication(key_vals=out, area=self.area)

    def _dump_difference(
        self,
        my_key_vals: Dict[str, Value],
        req_key_vals: Dict[str, Value],
    ) -> Publication:
        """reference: KvStore.cpp:1351 dumpDifference — keyVals: keys where
        we are better/only; tobe_updated_keys: keys where requester is
        better/only (so the requester can push them back)."""
        key_vals: Dict[str, Value] = {}
        tobe_updated: List[str] = []
        for key in set(my_key_vals) | set(req_key_vals):
            mine = my_key_vals.get(key)
            req = req_key_vals.get(key)
            if mine is None:
                tobe_updated.append(key)
                continue
            if req is None:
                key_vals[key] = mine
                continue
            rc = compare_values(mine, req)
            if rc in (1, -2):
                key_vals[key] = mine
            if rc in (-1, -2):
                tobe_updated.append(key)
        return Publication(
            key_vals=self._update_publication_ttl(key_vals),
            tobe_updated_keys=sorted(tobe_updated),
            area=self.area,
        )

    def _update_publication_ttl(
        self, key_vals: Dict[str, Value]
    ) -> Dict[str, Value]:
        """Rewrite TTLs to remaining time; drop keys about to expire.
        reference: KvStore.cpp updatePublicationTtl."""
        now = time.monotonic()
        expiry: Dict[str, float] = {}
        for exp, key, version, orig, ttlv in self._ttl_heap:
            stored = self.key_vals.get(key)
            if (
                stored is not None
                and stored.version == version
                and stored.originator_id == orig
                and stored.ttl_version == ttlv
            ):
                expiry[key] = exp
        out: Dict[str, Value] = {}
        for key, v in key_vals.items():
            if v.ttl == TTL_INFINITY:
                out[key] = v
                continue
            exp = expiry.get(key)
            remaining = (
                v.ttl - TTL_DECREMENT_MS
                if exp is None
                else int((exp - now) * 1000) - TTL_DECREMENT_MS
            )
            if remaining <= 0:
                continue
            out[key] = Value(
                version=v.version,
                originator_id=v.originator_id,
                value=v.value,
                ttl=remaining,
                ttl_version=v.ttl_version,
                hash=v.hash,
            )
        return out

    # -- peers + full sync ------------------------------------------------

    def add_peer(self, name: str, transport: PeerTransport) -> None:
        peer = self.peers.get(name)
        if peer is None:
            self.peers[name] = _Peer(name=name, transport=transport)
        else:
            if (
                self.dual is not None
                and peer.state == KvStorePeerState.INITIALIZED
            ):
                # re-peering demotes to IDLE: balance the earlier peer_up
                self._send_dual(self.dual.peer_down(name))
            peer.transport = transport
            peer.state = KvStorePeerState.IDLE
        self._request_sync()

    def del_peer(self, name: str) -> None:
        peer = self.peers.pop(name, None)
        if (
            self.dual is not None
            and peer is not None
            and peer.state == KvStorePeerState.INITIALIZED
        ):
            self._send_dual(self.dual.peer_down(name))

    def peer_states(self) -> Dict[str, KvStorePeerState]:
        return {name: p.state for name, p in self.peers.items()}

    def peer_endpoints(self) -> Dict[str, Optional[Tuple[str, int]]]:
        return {
            name: p.transport.endpoint for name, p in self.peers.items()
        }

    def _request_sync(self) -> None:
        """Promote IDLE peers to SYNCING and kick the 3-way full sync
        (reference: KvStore.cpp:1400 requestThriftPeerSync)."""
        for peer in list(self.peers.values()):
            if peer.state != KvStorePeerState.IDLE:
                continue
            if not peer.backoff.can_try_now():
                self._evb.schedule_timeout(
                    peer.backoff.get_time_remaining_until_retry(),
                    self._request_sync,
                )
                continue
            peer.state = KvStorePeerState.SYNCING
            peer.sync_started = time.monotonic()
            self.counters["kvstore.full_sync_count"] += 1
            hashes = self.dump_hashes().key_vals
            params = KeyDumpParams(key_val_hashes=hashes)

            def do_sync(peer=peer, params=params) -> None:
                try:
                    fault_point(FAULT_KV_FULL_SYNC)
                    pub = peer.transport.get_key_vals_filtered(self.area, params)
                except Exception:
                    self._evb.run_in_event_base(
                        lambda: self._sync_failed(peer.name)
                    )
                    return
                self._evb.run_in_event_base(
                    lambda: self._sync_succeeded(peer.name, pub)
                )

            self._executor.submit(do_sync)

    def _sync_failed(self, peer_name: str) -> None:
        self.counters["kvstore.full_sync_failures"] += 1
        get_registry().counter_bump("kvstore.full_sync_failures")
        peer = self.peers.get(peer_name)
        if peer is None:
            return
        peer.state = KvStorePeerState.IDLE
        peer.backoff.report_error()
        self._evb.schedule_timeout(
            peer.backoff.get_time_remaining_until_retry(), self._request_sync
        )

    def _sync_succeeded(self, peer_name: str, pub: Publication) -> None:
        """reference: KvStore.cpp:1554 processThriftSuccess."""
        peer = self.peers.get(peer_name)
        if peer is None:
            return
        peer.state = KvStorePeerState.INITIALIZED
        peer.backoff.report_success()
        if peer.sync_started is not None:
            self._log_sample(
                event="KVSTORE_FULL_SYNC",
                neighbor=peer_name,
                duration_ms=int(
                    (time.monotonic() - peer.sync_started) * 1000
                ),
            )
            peer.sync_started = None
        if self.dual is not None:
            # (re-)announce the link to DUAL; a bounced peer is handled
            # as down-then-up inside Dual.peer_up
            self._send_dual(self.dual.peer_up(peer.name, cost=1))
        # merge what the peer had better; reflood to *other* peers
        self.set_key_vals(
            KeySetParams(key_vals=pub.key_vals, originator_id=peer_name),
            sender_id=peer_name,
        )
        # 3rd leg: push back the keys we are better at
        if pub.tobe_updated_keys:
            self._finalize_full_sync(peer, pub.tobe_updated_keys)
        # flush floods that raced the full sync
        if peer.pending_flood:
            pending, peer.pending_flood = peer.pending_flood, {}
            params = KeySetParams(
                key_vals=pending,
                originator_id=self.node_id,
                solicit_response=False,
            )
            self._async_peer_call(
                peer,
                lambda t=peer.transport: t.set_key_vals(self.area, params),
            )

    def _finalize_full_sync(self, peer: _Peer, keys: List[str]) -> None:
        """reference: KvStore.cpp:2727 finalizeFullSync."""
        updates = {
            key: self.key_vals[key] for key in keys if key in self.key_vals
        }
        updates = self._update_publication_ttl(updates)
        if not updates:
            return
        params = KeySetParams(
            key_vals=updates,
            originator_id=self.node_id,
            solicit_response=False,
        )
        self._async_peer_call(
            peer, lambda t=peer.transport: t.set_key_vals(self.area, params)
        )

    def _on_dual_nexthop_change(
        self, root_id: str, old_nh: Optional[str], new_nh: Optional[str]
    ) -> None:
        """Our SPT parent for root_id changed: tell the old parent to
        drop us as a child and the new one to adopt us (reference:
        KvStoreDb::processNexthopChange sending FLOOD_TOPO_SET)."""
        for nh, is_set in ((old_nh, False), (new_nh, True)):
            if nh is None or nh == self.node_id:
                continue
            peer = self.peers.get(nh)
            if peer is None:
                continue
            self._async_peer_call(
                peer,
                lambda t=peer.transport, flag=is_set: t.set_flood_topo_child(
                    self.area, root_id, self.node_id, flag
                ),
            )

    def set_flood_topo_child(
        self, root_id: str, child_id: str, is_set: bool,
        all_roots: bool = False,
    ) -> None:
        """A peer (un)registered as our SPT child (reference:
        KvStoreDb::processFloodTopoSet; ``all_roots`` applies the
        change to every root, FloodTopoSetParams.allRoots)."""
        if self.dual is None:
            return
        if all_roots:
            for rid in list(self.dual.duals):
                self.set_flood_topo_child(rid, child_id, is_set)
            return
        dual = self.dual.get_dual(root_id)
        if dual is None:
            return
        if is_set:
            dual.add_child(child_id)
        else:
            dual.remove_child(child_id)

    def process_dual_messages(self, sender: str, msgs) -> None:
        """Incoming DUAL messages from a peer (reference:
        processKvStoreDualMessage); replies/propagation go back out over
        the peer transports."""
        if self.dual is None:
            return
        for msg in msgs:
            self._send_dual(self.dual.process_message(sender, msg))

    def _send_dual(self, out_msgs) -> None:
        for nbr, mlist in out_msgs.items():
            peer = self.peers.get(nbr)
            if peer is None or not mlist:
                continue
            self._async_peer_call(
                peer,
                lambda t=peer.transport, m=list(mlist): t.send_dual_messages(
                    self.area, self.node_id, m
                ),
            )

    def _async_peer_call(self, peer: _Peer, call: Callable[[], None]) -> None:
        def run() -> None:
            try:
                call()
            except Exception:
                self._evb.run_in_event_base(lambda: self._peer_io_failed(peer.name))

        self._executor.submit(run)

    def _peer_io_failed(self, peer_name: str) -> None:
        self.counters["kvstore.flood_errors"] += 1
        get_registry().counter_bump("kvstore.flood_errors")
        peer = self.peers.get(peer_name)
        if peer is None:
            return
        if (
            self.dual is not None
            and peer.state == KvStorePeerState.INITIALIZED
        ):
            self._send_dual(self.dual.peer_down(peer_name))
        peer.state = KvStorePeerState.IDLE
        peer.backoff.report_error()
        self._evb.schedule_timeout(
            peer.backoff.get_time_remaining_until_retry(), self._request_sync
        )


class KvStore:
    """The KvStore module: one event base, one KvStoreDb per area.
    Public APIs are thread-safe (marshalled onto the module thread, the
    analogue of the reference's folly::SemiFuture APIs)."""

    def __init__(
        self,
        node_id: str,
        areas: Optional[List[str]] = None,
        updates_queue: Optional[ReplicateQueue] = None,
        filters: Optional[KvStoreFilters] = None,
        sync_interval_s: float = 60.0,
        enable_flood_optimization: bool = False,
        is_flood_root: bool = False,
        flood_rate: Optional[Tuple[float, int]] = None,
        log_sample_queue: Optional[ReplicateQueue] = None,
        state_plane=None,
    ):
        self.node_id = node_id
        self.evb = OpenrEventBase(name=f"kvstore:{node_id}")
        self.updates_queue = updates_queue or ReplicateQueue(
            name=f"kvstoreUpdates:{node_id}"
        )
        self._executor = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix=f"kvstore-io:{node_id}"
        )
        self._dbs: Dict[str, KvStoreDb] = {}
        for area in areas or [DEFAULT_AREA]:
            self._dbs[area] = KvStoreDb(
                area,
                node_id,
                self.evb,
                self.updates_queue,
                self._executor,
                filters,
                enable_flood_optimization=enable_flood_optimization,
                is_flood_root=is_flood_root,
                flood_rate=flood_rate,
                log_sample_queue=log_sample_queue,
                merge_hook=(
                    state_plane.on_kvstore_merge
                    if state_plane is not None
                    else None
                ),
            )
        self._sync_interval = sync_interval_s
        self._sync_timer = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self.evb.run_in_thread()
        self._sync_timer = self.evb.schedule_periodic(
            self._sync_interval, self._periodic_sync, jitter_first=True
        )

    def stop(self) -> None:
        if self._sync_timer is not None:
            self._sync_timer.cancel()
        self.evb.stop()
        self.evb.join()
        self._executor.shutdown(wait=False)

    def _periodic_sync(self) -> None:
        """Anti-entropy: retry IDLE peers (reference: KvStore.cpp:1942
        requestFullSyncFromPeers / periodic random resync)."""
        for db in self._dbs.values():
            db._request_sync()

    # -- area access ------------------------------------------------------

    def _db(self, area: str) -> KvStoreDb:
        if area not in self._dbs:
            raise KeyError(f"unknown area {area!r}")
        return self._dbs[area]

    def areas(self) -> List[str]:
        return sorted(self._dbs)

    # -- public API (thread-safe) -----------------------------------------

    def set_key_vals(
        self, area: str, params: KeySetParams, sender_id: Optional[str] = None
    ) -> None:
        self.evb.call_and_wait(
            lambda: self._db(area).set_key_vals(params, sender_id)
        )

    def get_key_vals(self, area: str, keys: List[str]) -> Dict[str, Value]:
        return self.evb.call_and_wait(
            lambda: {
                k: self._db(area).key_vals[k]
                for k in keys
                if k in self._db(area).key_vals
            }
        )

    def dump_with_filters(
        self, area: str, params: Optional[KeyDumpParams] = None
    ) -> Publication:
        params = params or KeyDumpParams()
        return self.evb.call_and_wait(
            lambda: self._db(area).dump_with_filters(params)
        )

    def dump_hashes(self, area: str, prefix: str = "") -> Publication:
        return self.evb.call_and_wait(lambda: self._db(area).dump_hashes(prefix))

    def add_peer(self, area: str, name: str, transport: PeerTransport) -> None:
        self.evb.call_and_wait(lambda: self._db(area).add_peer(name, transport))

    def del_peer(self, area: str, name: str) -> None:
        self.evb.call_and_wait(lambda: self._db(area).del_peer(name))

    def peer_states(self, area: str) -> Dict[str, KvStorePeerState]:
        return self.evb.call_and_wait(lambda: self._db(area).peer_states())

    def peer_endpoints(
        self, area: str
    ) -> Dict[str, Optional[Tuple[str, int]]]:
        return self.evb.call_and_wait(
            lambda: self._db(area).peer_endpoints()
        )

    def spt_infos(self, area: str) -> Dict:
        """Flood-topology snapshot for the ctrl getSpanningTreeInfos
        RPC (reference: KvStore.thrift SptInfos + KvStore.cpp
        processFloodTopoGet): per-root passive/cost/parent/children,
        the elected flood root, and the flooding peer set. Empty when
        flood optimization is off."""

        def snap() -> Dict:
            db = self._db(area)
            if db.dual is None:
                return {"infos": {}, "flood_root_id": None,
                        "flood_peers": set()}
            from openr_tpu.dual.dual import DualState

            infos = {}
            for root, dual in db.dual.duals.items():
                infos[root] = {
                    "passive": dual.sm.state == DualState.PASSIVE,
                    "cost": int(dual.distance),
                    "parent": dual.nexthop,
                    "children": dual.children(),
                }
            root = db.dual.pick_flood_root()
            return {
                "infos": infos,
                "flood_root_id": root,
                "flood_peers": (
                    db.dual.spt_peers(root) if root is not None else set()
                ),
            }

        return self.evb.call_and_wait(snap)

    def process_dual_messages(self, area: str, sender: str, msgs) -> None:
        self.evb.call_and_wait(
            lambda: self._db(area).process_dual_messages(sender, msgs)
        )

    def set_flood_topo_child(
        self, area: str, root_id: str, child_id: str, is_set: bool,
        all_roots: bool = False,
    ) -> None:
        self.evb.call_and_wait(
            lambda: self._db(area).set_flood_topo_child(
                root_id, child_id, is_set, all_roots=all_roots
            )
        )

    def counters(self) -> Dict[str, int]:
        def collect():
            out: Dict[str, int] = {}
            for db in self._dbs.values():
                for k, v in db.counters.items():
                    out[k] = out.get(k, 0) + v
                out[f"kvstore.num_keys.{db.area}"] = len(db.key_vals)
            return out

        return self.evb.call_and_wait(collect)
