"""Thrift-wire KvStore peer channel: framed CompactProtocol RPC.

The reference's modern peer path is a thrift ``KvStoreService``
(openr/if/KvStore.thrift:256-276; dual-stacked with legacy fbzmq in
KvStore.cpp:2940-2973). This module implements that service's wire
contract in the standard Apache-thrift encoding every thrift toolchain
ships — TFramedTransport (4-byte big-endian length prefix) carrying
TCompactProtocol messages — so a stock thrift client with the
KvStore.thrift IDL can sync against this daemon, and this daemon's
client can sync against any framed+compact KvStoreService server.

Message envelope (TCompactProtocol::writeMessageBegin):

    0x82 | (version=1 | type<<5) | varint(seqid) | varstring(name)

followed by the args struct; replies carry a result struct whose
success field is id 0. (fbthrift's default Rocket/THeader transports
are a different outer layer; classic framed transport is the
interop-stable one, and fbthrift servers accept it in compatibility
mode.)

Methods served (KvStore.thrift:256-276, OpenrCtrl.thrift:358-381):
- ``getKvStoreKeyValsFilteredArea(1: KeyDumpParams filter, 2: string area)``
- ``getKvStoreKeyValsArea(1: list<string> filterKeys, 2: string area)``
- ``setKvStoreKeyVals(1: KeySetParams setParams, 2: string area)``
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Dict, Optional, Tuple

from openr_tpu.kvstore.store import KvStore, PeerTransport
from openr_tpu.types import KeyDumpParams, KeySetParams, Publication
from openr_tpu.utils import thrift_compact as tc
from openr_tpu.utils.rpc import apply_bind_family

PROTOCOL_ID = 0x82
VERSION = 1
TYPE_CALL = 1
TYPE_REPLY = 2
TYPE_EXCEPTION = 3

# TApplicationException (thrift builtin), compact-encoded
_TAPP_EXC = tc.StructSchema(
    "TApplicationException",
    (
        tc.Field(1, ("string",), "message", optional=True),
        tc.Field(2, ("i32",), "type", optional=True),
    ),
)

_GET_ARGS = tc.StructSchema(
    "getKvStoreKeyValsFilteredArea_args",
    (
        tc.Field(1, ("struct", tc.KEY_DUMP_PARAMS), "filter"),
        tc.Field(2, ("string",), "area"),
    ),
)
_GET_RESULT = tc.StructSchema(
    "getKvStoreKeyValsFilteredArea_result",
    (tc.Field(0, ("struct", tc.PUBLICATION), "success", optional=True),),
)
_SET_ARGS = tc.StructSchema(
    "setKvStoreKeyVals_args",
    (
        tc.Field(1, ("struct", tc.KEY_SET_PARAMS), "setParams"),
        tc.Field(2, ("string",), "area"),
    ),
)
_SET_RESULT = tc.StructSchema("setKvStoreKeyVals_result", ())
_GET_KEYS_ARGS = tc.StructSchema(
    "getKvStoreKeyValsArea_args",
    (
        tc.Field(1, ("list", ("string",)), "filterKeys"),
        tc.Field(2, ("string",), "area"),
    ),
)


def encode_message(
    name: str, mtype: int, seqid: int, schema, values: Dict
) -> bytes:
    """One framed compact-protocol message (frame header excluded)."""
    w = tc._Writer()
    w.byte(PROTOCOL_ID)
    w.byte((VERSION & 0x1F) | (mtype << 5))
    w.varint(seqid)
    w.binary(name.encode("utf-8"))
    return bytes(w.buf) + tc.encode(schema, values)


def decode_message_header(data: bytes) -> Tuple[str, int, int, int]:
    """Returns (name, mtype, seqid, args_offset)."""
    r = tc._Reader(data)
    proto = r.byte()
    if proto != PROTOCOL_ID:
        raise ValueError(f"not a compact-protocol message: 0x{proto:02x}")
    vt = r.byte()
    if (vt & 0x1F) != VERSION:
        raise ValueError(f"unsupported compact version {vt & 0x1F}")
    mtype = (vt >> 5) & 0x07
    seqid = r.varint()
    name = r.binary().decode("utf-8")
    return name, mtype, seqid, r.pos


def _frame(payload: bytes) -> bytes:
    return struct.pack(">I", len(payload)) + payload


def _read_frame(sock: socket.socket) -> Optional[bytes]:
    hdr = _read_exact(sock, 4)
    if hdr is None:
        return None
    (length,) = struct.unpack(">I", hdr)
    if length > 64 * 1024 * 1024:
        raise ValueError(f"oversized frame {length}")
    return _read_exact(sock, length)


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    # bytearray accumulation: += on bytes is quadratic, and full-sync
    # publications can be tens of MB
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class KvStoreThriftPeerServer:
    """Serve the KvStoreService peer surface over framed+compact TCP."""

    def __init__(self, kvstore: KvStore, host: str = "0.0.0.0",
                 port: int = 0):
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                while True:
                    try:
                        frame = _read_frame(self.request)
                    except (OSError, ValueError):
                        return
                    if frame is None:
                        return
                    try:
                        reply = outer._dispatch(frame)
                    except Exception as exc:
                        # thrift-standard error path: reply with a
                        # TApplicationException instead of slamming the
                        # connection (a stock client expects a reply
                        # frame, not a bare EOF)
                        reply = outer._exception_reply(frame, exc)
                        if reply is None:  # header itself unparseable
                            return
                    try:
                        self.request.sendall(_frame(reply))
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        apply_bind_family(Server, host)
        self._kvstore = kvstore
        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _exception_reply(frame: bytes, exc: Exception) -> Optional[bytes]:
        try:
            name, _mtype, seqid, _off = decode_message_header(frame)
        except Exception:
            return None
        return encode_message(
            name, TYPE_EXCEPTION, seqid, _TAPP_EXC,
            {"message": f"{type(exc).__name__}: {exc}", "type": 6},
        )

    def _dispatch(self, frame: bytes) -> bytes:
        name, mtype, seqid, off = decode_message_header(frame)
        if mtype != TYPE_CALL:
            raise ValueError(f"unexpected message type {mtype}")
        body = frame[off:]
        params = None
        if name == "getKvStoreKeyValsFilteredArea":
            args = tc.decode(_GET_ARGS, body)
            params = tc._key_dump_params_from_wire(args.get("filter", {}))
        elif name == "getKvStoreKeyValsArea":
            # plain keyed get (OpenrCtrl.thrift:364): modeled as a
            # filtered dump restricted to exact keys. An EMPTY key list
            # asks for nothing — dump_with_filters treats falsy keys as
            # "no filter", which would ship the whole database instead
            # (the in-process exact get returns {} here)
            args = tc.decode(_GET_KEYS_ARGS, body)
            keys = args.get("filterKeys", [])
            if not keys:
                return encode_message(
                    name, TYPE_REPLY, seqid, _GET_RESULT,
                    {
                        "success": tc._publication_to_wire(
                            Publication(area=args.get("area", ""))
                        )
                    },
                )
            params = KeyDumpParams(keys=keys)
        if params is not None:
            pub = self._kvstore.dump_with_filters(
                args.get("area", ""), params
            )
            return encode_message(
                name, TYPE_REPLY, seqid, _GET_RESULT,
                {"success": tc._publication_to_wire(pub)},
            )
        if name == "setKvStoreKeyVals":
            args = tc.decode(_SET_ARGS, body)
            params = tc._key_set_params_from_wire(
                args.get("setParams", {})
            )
            self._kvstore.set_key_vals(
                args.get("area", ""),
                params,
                sender_id=params.originator_id,
            )
            return encode_message(
                name, TYPE_REPLY, seqid, _SET_RESULT, {}
            )
        return encode_message(
            name, TYPE_EXCEPTION, seqid, _TAPP_EXC,
            {"message": f"unknown method {name!r}", "type": 1},
        )

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="kvstore-thrift-peer",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None


class ThriftPeerTransport(PeerTransport):
    """Dial a framed+compact KvStoreService peer (this framework's
    server above, or any thrift server with the same IDL)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self._addr = (host, port)
        self._timeout_s = timeout_s
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._seqid = 0

    def _connect(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                self._addr, timeout=self._timeout_s
            )
        return self._sock

    def _call(self, name: str, args_schema, args: Dict,
              result_schema) -> Dict:
        with self._lock:
            self._seqid += 1
            seqid = self._seqid
            payload = encode_message(
                name, TYPE_CALL, seqid, args_schema, args
            )
            try:
                sock = self._connect()
                sock.sendall(_frame(payload))
                frame = _read_frame(sock)
            except OSError:
                self.close()
                raise
            if frame is None:
                self.close()
                raise ConnectionError("peer closed mid-call")
            rname, mtype, rseq, off = decode_message_header(frame)
            if mtype == TYPE_EXCEPTION:
                exc = tc.decode(_TAPP_EXC, frame[off:])
                raise RuntimeError(
                    f"peer exception: {exc.get('message')}"
                )
            if rname != name or rseq != seqid:
                self.close()
                raise ConnectionError(
                    f"out-of-sync reply {rname}/{rseq}"
                )
            return tc.decode(result_schema, frame[off:])

    # -- PeerTransport -----------------------------------------------------

    def _call_publication(self, name, schema, args: Dict) -> Publication:
        """Call a Publication-returning method; a reply without the
        success field means the peer raised a declared IDL exception
        this schema does not model — fabricating an empty Publication
        would mark the peer synced with zero keys, so raise instead
        (standard generated clients raise MISSING_RESULT here)."""
        result = self._call(name, schema, args, _GET_RESULT)
        if "success" not in result:
            raise RuntimeError(
                f"{name} returned no result "
                "(peer raised a declared exception)"
            )
        return tc._publication_from_wire(result["success"])

    def get_key_vals_filtered(
        self, area: str, params: KeyDumpParams
    ) -> Publication:
        return self._call_publication(
            "getKvStoreKeyValsFilteredArea",
            _GET_ARGS,
            {
                "filter": tc._key_dump_params_to_wire(params),
                "area": area,
            },
        )

    def get_key_vals(self, area: str, keys) -> Publication:
        """Plain keyed get (OpenrCtrl.thrift:364
        getKvStoreKeyValsArea)."""
        return self._call_publication(
            "getKvStoreKeyValsArea",
            _GET_KEYS_ARGS,
            {"filterKeys": list(keys), "area": area},
        )

    def set_key_vals(self, area: str, params: KeySetParams) -> None:
        self._call(
            "setKvStoreKeyVals",
            _SET_ARGS,
            {
                "setParams": tc._key_set_params_to_wire(params),
                "area": area,
            },
            _SET_RESULT,
        )

    def send_dual_messages(self, area, sender_id, msgs) -> None:
        raise NotImplementedError(
            "DUAL flood-optimization rides the framework RPC channel "
            "(kvstore.transport); the thrift peer channel covers the "
            "sync/flood surface"
        )

    def set_flood_topo_child(self, area, root_id, child, is_child) -> None:
        raise NotImplementedError(
            "flood-topo updates ride the framework RPC channel"
        )

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
