"""Thrift-wire KvStore peer channel: framed CompactProtocol RPC.

The reference's modern peer path is a thrift ``KvStoreService``
(openr/if/KvStore.thrift:256-276; dual-stacked with legacy fbzmq in
KvStore.cpp:2940-2973). This module serves/dials that service's wire
contract in the standard Apache-thrift encoding every thrift toolchain
ships (shared transport + message envelope: utils/thrift_rpc.py), so a
stock thrift client with the KvStore.thrift IDL can sync against this
daemon, and this daemon's client can sync against any framed+compact
KvStoreService server.

Methods served (KvStore.thrift:256-276, OpenrCtrl.thrift:358-427):
- ``getKvStoreKeyValsFilteredArea(1: KeyDumpParams filter, 2: string area)``
- ``getKvStoreKeyValsArea(1: list<string> filterKeys, 2: string area)``
- ``setKvStoreKeyVals(1: KeySetParams setParams, 2: string area)``
- ``processKvStoreDualMessage(1: DualMessages, 2: string area)`` — the
  flood-optimization channel (reference carries DUAL on the same peer
  wire, KvStore.thrift:47-52 Command.DUAL / OpenrCtrl.thrift:416)
- ``updateFloodTopologyChild(1: FloodTopoSetParams, 2: string area)``
"""

from __future__ import annotations

from typing import Dict

from openr_tpu.kvstore.store import KvStore, PeerTransport
from openr_tpu.types import KeyDumpParams, KeySetParams, Publication
from openr_tpu.utils import thrift_compact as tc
from openr_tpu.utils.thrift_rpc import (
    FramedCompactClient,
    FramedCompactServer,
)

_GET_ARGS = tc.StructSchema(
    "getKvStoreKeyValsFilteredArea_args",
    (
        tc.Field(1, ("struct", tc.KEY_DUMP_PARAMS), "filter"),
        tc.Field(2, ("string",), "area"),
    ),
)
_GET_RESULT = tc.StructSchema(
    "getKvStoreKeyValsFilteredArea_result",
    (tc.Field(0, ("struct", tc.PUBLICATION), "success", optional=True),),
)
_SET_ARGS = tc.StructSchema(
    "setKvStoreKeyVals_args",
    (
        tc.Field(1, ("struct", tc.KEY_SET_PARAMS), "setParams"),
        tc.Field(2, ("string",), "area"),
    ),
)
_SET_RESULT = tc.StructSchema("setKvStoreKeyVals_result", ())
_GET_KEYS_ARGS = tc.StructSchema(
    "getKvStoreKeyValsArea_args",
    (
        tc.Field(1, ("list", ("string",)), "filterKeys"),
        tc.Field(2, ("string",), "area"),
    ),
)
_DUAL_ARGS = tc.StructSchema(
    "processKvStoreDualMessage_args",
    (
        tc.Field(1, ("struct", tc.DUAL_MESSAGES), "messages"),
        tc.Field(2, ("string",), "area"),
    ),
)
_DUAL_RESULT = tc.StructSchema("processKvStoreDualMessage_result", ())
_FLOOD_TOPO_ARGS = tc.StructSchema(
    "updateFloodTopologyChild_args",
    (
        tc.Field(1, ("struct", tc.FLOOD_TOPO_SET_PARAMS), "params"),
        tc.Field(2, ("string",), "area"),
    ),
)
_FLOOD_TOPO_RESULT = tc.StructSchema(
    "updateFloodTopologyChild_result", ()
)


class KvStoreThriftPeerServer:
    """Serve the KvStoreService peer surface over framed+compact TCP."""

    def __init__(self, kvstore: KvStore, host: str = "0.0.0.0",
                 port: int = 0, listen: bool = True):
        self._kvstore = kvstore
        self._server = FramedCompactServer(
            {
                "getKvStoreKeyValsFilteredArea": (
                    _GET_ARGS, self._get_filtered,
                ),
                "getKvStoreKeyValsArea": (_GET_KEYS_ARGS, self._get_keys),
                "setKvStoreKeyVals": (_SET_ARGS, self._set),
                "processKvStoreDualMessage": (_DUAL_ARGS, self._dual),
                "updateFloodTopologyChild": (
                    _FLOOD_TOPO_ARGS, self._flood_topo,
                ),
            },
            host=host,
            port=port,
            listen=listen,
        )
        self.port = self._server.port

    def _pub_reply(self, pub: Publication):
        return _GET_RESULT, {"success": tc._publication_to_wire(pub)}

    def _get_filtered(self, args: Dict):
        params = tc._key_dump_params_from_wire(args.get("filter", {}))
        return self._pub_reply(
            self._kvstore.dump_with_filters(args.get("area", ""), params)
        )

    def _get_keys(self, args: Dict):
        # plain keyed get (OpenrCtrl.thrift:364): a filtered dump
        # restricted to exact keys. An EMPTY key list asks for nothing —
        # dump_with_filters treats falsy keys as "no filter", which
        # would ship the whole database instead (the in-process exact
        # get returns {} here)
        keys = args.get("filterKeys", [])
        if not keys:
            return self._pub_reply(Publication(area=args.get("area", "")))
        return self._pub_reply(
            self._kvstore.dump_with_filters(
                args.get("area", ""), KeyDumpParams(keys=keys)
            )
        )

    def _set(self, args: Dict):
        params = tc._key_set_params_from_wire(args.get("setParams", {}))
        self._kvstore.set_key_vals(
            args.get("area", ""), params, sender_id=params.originator_id
        )
        return _SET_RESULT, {}

    def _dual(self, args: Dict):
        src_id, msgs = tc.dual_messages_from_wire(
            args.get("messages", {})
        )
        self._kvstore.process_dual_messages(
            args.get("area", ""), src_id, msgs
        )
        return _DUAL_RESULT, {}

    def _flood_topo(self, args: Dict):
        params = args.get("params", {})
        self._kvstore.set_flood_topo_child(
            args.get("area", ""),
            params.get("rootId", ""),
            params.get("srcId", ""),
            params.get("setChild", False),
            all_roots=params.get("allRoots", False),
        )
        return _FLOOD_TOPO_RESULT, {}

    def serve_connection(self, sock) -> None:
        self._server.serve_connection(sock)

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop()


class ThriftPeerTransport(PeerTransport):
    """Dial a framed+compact KvStoreService peer (this framework's
    server above, or any thrift server with the same IDL)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self._client = FramedCompactClient(host, port, timeout_s)
        self.endpoint = (host, port)

    def _call_publication(self, name, schema, args: Dict) -> Publication:
        """Call a Publication-returning method; a reply without the
        success field means the peer raised a declared IDL exception
        this schema does not model — fabricating an empty Publication
        would mark the peer synced with zero keys, so raise instead
        (standard generated clients raise MISSING_RESULT here)."""
        result = self._client.call(name, schema, args, _GET_RESULT)
        if "success" not in result:
            raise RuntimeError(
                f"{name} returned no result "
                "(peer raised a declared exception)"
            )
        return tc._publication_from_wire(result["success"])

    # -- PeerTransport -----------------------------------------------------

    def get_key_vals_filtered(
        self, area: str, params: KeyDumpParams
    ) -> Publication:
        return self._call_publication(
            "getKvStoreKeyValsFilteredArea",
            _GET_ARGS,
            {
                "filter": tc._key_dump_params_to_wire(params),
                "area": area,
            },
        )

    def get_key_vals(self, area: str, keys) -> Publication:
        """Plain keyed get (OpenrCtrl.thrift:364
        getKvStoreKeyValsArea)."""
        return self._call_publication(
            "getKvStoreKeyValsArea",
            _GET_KEYS_ARGS,
            {"filterKeys": list(keys), "area": area},
        )

    def set_key_vals(self, area: str, params: KeySetParams) -> None:
        self._client.call(
            "setKvStoreKeyVals",
            _SET_ARGS,
            {
                "setParams": tc._key_set_params_to_wire(params),
                "area": area,
            },
            _SET_RESULT,
        )

    def send_dual_messages(self, area, sender_id, msgs) -> None:
        """DUAL messages on the SAME peer channel, as the reference
        does (Command.DUAL, KvStore.thrift:47-52; service method
        OpenrCtrl.thrift:416 processKvStoreDualMessage)."""
        self._client.call(
            "processKvStoreDualMessage",
            _DUAL_ARGS,
            {
                "messages": tc.dual_messages_to_wire(sender_id, msgs),
                "area": area,
            },
            _DUAL_RESULT,
        )

    def set_flood_topo_child(self, area, root_id, child, is_child) -> None:
        """reference: OpenrCtrl.thrift:424 updateFloodTopologyChild."""
        self._client.call(
            "updateFloodTopologyChild",
            _FLOOD_TOPO_ARGS,
            {
                "params": {
                    "rootId": root_id,
                    "srcId": child,
                    "setChild": is_child,
                },
                "area": area,
            },
            _FLOOD_TOPO_RESULT,
        )

    def close(self) -> None:
        self._client.close()
