"""KvStoreClient: in-process client used by the other modules.

Behavioral parity with the reference ``openr/kvstore/KvStoreClientInternal``:
- ``persist_key``: own a key — advertise it, refresh its TTL, and win back
  ownership (higher version) if any other node overwrites it
- ``set_key`` / ``get_key`` / ``dump_all_with_prefix``
- per-key and filtered subscription callbacks fed from the store's
  publication queue, delivered on the caller's event base
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

from openr_tpu.types import (
    TTL_INFINITY,
    KeyDumpParams,
    KeySetParams,
    Publication,
    Value,
)
from openr_tpu.utils.eventbase import OpenrEventBase

KeyCallback = Callable[[str, Optional[Value]], None]


@dataclass
class _PersistedKey:
    area: str
    key: str
    value: bytes
    ttl: int


class KvStoreClient:
    def __init__(
        self,
        evb: OpenrEventBase,
        node_id: str,
        kvstore,
        ttl_refresh_interval_s: float = 0.5,
    ):
        self._evb = evb
        self._node_id = node_id
        self._kvstore = kvstore
        self._persisted: Dict[Tuple[str, str], _PersistedKey] = {}
        self._key_callbacks: Dict[Tuple[str, str], KeyCallback] = {}
        self._filter_callbacks: list = []
        reader = kvstore.updates_queue.get_reader(f"client:{node_id}")
        evb.add_queue_reader(reader, self._process_publication)
        self._refresh_timer = evb.schedule_periodic(
            ttl_refresh_interval_s, self._refresh_ttls, jitter_first=True
        )

    @property
    def evb(self) -> OpenrEventBase:
        """The event base publications are delivered on — consensus users
        (RangeAllocator) must run their FSM on this same thread."""
        return self._evb

    def stop(self) -> None:
        self._refresh_timer.cancel()

    # -- key ownership ----------------------------------------------------

    def persist_key(
        self, area: str, key: str, value: bytes, ttl: int = TTL_INFINITY
    ) -> None:
        """Advertise and keep ownership of key (reference:
        KvStoreClientInternal::persistKey)."""
        self._persisted[(area, key)] = _PersistedKey(area, key, value, ttl)
        existing = self.get_key(area, key)
        version = 1
        if existing is not None:
            if (
                existing.originator_id == self._node_id
                and existing.value == value
            ):
                return  # already ours with same value
            version = existing.version + 1
        self._advertise(area, key, value, version, ttl)

    def unset_key(self, area: str, key: str) -> None:
        """Stop owning the key; it will age out via TTL (there is no
        delete in the flooded store)."""
        self._persisted.pop((area, key), None)

    def clear_key(
        self, area: str, key: str, value: bytes, ttl: int = TTL_INFINITY
    ) -> None:
        """Stop owning the key and flood one final tombstone value
        (reference: KvStoreClientInternal::clearKey). Ownership must be
        dropped *before* the tombstone floods, or the ownership
        enforcement in _process_publication would see a foreign value on
        a persisted key and resurrect the old one."""
        self._persisted.pop((area, key), None)
        self.set_key(area, key, value, ttl=ttl)

    def set_key(
        self,
        area: str,
        key: str,
        value: bytes,
        version: Optional[int] = None,
        ttl: int = TTL_INFINITY,
    ) -> None:
        if version is None:
            existing = self.get_key(area, key)
            version = 1 if existing is None else existing.version + 1
        self._advertise(area, key, value, version, ttl)

    def _advertise(
        self, area: str, key: str, value: bytes, version: int, ttl: int
    ) -> None:
        self._kvstore.set_key_vals(
            area,
            KeySetParams(
                key_vals={
                    key: Value(
                        version=version,
                        originator_id=self._node_id,
                        value=value,
                        ttl=ttl,
                        ttl_version=0,
                    )
                },
                originator_id=self._node_id,
            ),
        )

    # -- reads ------------------------------------------------------------

    def get_key(self, area: str, key: str) -> Optional[Value]:
        return self._kvstore.get_key_vals(area, [key]).get(key)

    def dump_all_with_prefix(self, area: str, prefix: str = "") -> Dict[str, Value]:
        pub = self._kvstore.dump_with_filters(
            area, KeyDumpParams(prefix=prefix)
        )
        return pub.key_vals

    # -- subscriptions ----------------------------------------------------

    def subscribe_key(self, area: str, key: str, callback: KeyCallback) -> None:
        self._key_callbacks[(area, key)] = callback

    def unsubscribe_key(self, area: str, key: str) -> None:
        self._key_callbacks.pop((area, key), None)

    def unsubscribe_key_filter(self, callback) -> None:
        try:
            self._filter_callbacks.remove(callback)
        except ValueError:
            pass

    def subscribe_key_filter(
        self, callback: Callable[[str, str, Optional[Value]], None]
    ) -> None:
        """callback(area, key, value_or_None_for_expired)"""
        self._filter_callbacks.append(callback)

    # -- internals --------------------------------------------------------

    def _process_publication(self, pub: Publication) -> None:
        for key, value in pub.key_vals.items():
            cb = self._key_callbacks.get((pub.area, key))
            if cb is not None:
                cb(key, value)
            for fcb in self._filter_callbacks:
                fcb(pub.area, key, value)
            self._enforce_ownership(pub.area, key, value)
        for key in pub.expired_keys:
            cb = self._key_callbacks.get((pub.area, key))
            if cb is not None:
                cb(key, None)
            for fcb in self._filter_callbacks:
                fcb(pub.area, key, None)
            # re-advertise persisted keys that expired
            persisted = self._persisted.get((pub.area, key))
            if persisted is not None:
                self.persist_key(
                    pub.area, key, persisted.value, persisted.ttl
                )

    def _enforce_ownership(self, area: str, key: str, value: Value) -> None:
        """If someone overwrote a key we persist, advertise a higher
        version to win it back (reference: KvStoreClientInternal
        processPublication ownership enforcement)."""
        persisted = self._persisted.get((area, key))
        if persisted is None:
            return
        if value.value is None:
            return  # ttl-only refresh: carries no ownership information
        if (
            value.originator_id == self._node_id
            and value.value == persisted.value
        ):
            return
        self._advertise(
            area, key, persisted.value, value.version + 1, persisted.ttl
        )

    def refresh_ttl(self, area: str, key: str, ttl: int) -> bool:
        """One ttl-only refresh (same version, bumped ttlVersion, no
        value) for a key we originated. Returns False if the key is
        gone or no longer ours. Unlike persist_key this carries no
        ownership enforcement — consensus users (RangeAllocator) rely
        on the same-version merge ordering staying untouched."""
        current = self.get_key(area, key)
        if current is None or current.originator_id != self._node_id:
            return False
        self._kvstore.set_key_vals(
            area,
            KeySetParams(
                key_vals={
                    key: Value(
                        version=current.version,
                        originator_id=self._node_id,
                        value=None,  # ttl-only refresh
                        ttl=ttl,
                        ttl_version=current.ttl_version + 1,
                    )
                },
                originator_id=self._node_id,
            ),
        )
        return True

    def _refresh_ttls(self) -> None:
        """Bump ttlVersion on persisted finite-TTL keys so they never
        expire while owned."""
        for persisted in list(self._persisted.values()):
            if persisted.ttl == TTL_INFINITY:
                continue
            self.refresh_ttl(persisted.area, persisted.key, persisted.ttl)
