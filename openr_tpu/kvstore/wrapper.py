"""KvStoreWrapper: test fixture running a real KvStore.

Behavioral parity with the reference ``openr/kvstore/KvStoreWrapper.h``:
set/get keys, peer linking, and blocking publication receive — used to
build multi-store topologies (stars, rings, meshes) inside one process.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from openr_tpu.kvstore.store import InProcessTransport, KvStore
from openr_tpu.messaging.queue import RQueue
from openr_tpu.types import (
    DEFAULT_AREA,
    TTL_INFINITY,
    KeySetParams,
    Publication,
    Value,
)
from openr_tpu.utils import wire


class KvStoreWrapper:
    def __init__(
        self,
        node_id: str,
        areas: Optional[List[str]] = None,
        enable_flood_optimization: bool = False,
        is_flood_root: bool = False,
    ):
        self.node_id = node_id
        self.store = KvStore(
            node_id=node_id,
            areas=areas,
            enable_flood_optimization=enable_flood_optimization,
            is_flood_root=is_flood_root,
        )
        self._reader: RQueue = self.store.updates_queue.get_reader(
            f"wrapper:{node_id}"
        )

    def start(self) -> None:
        self.store.start()

    def stop(self) -> None:
        self.store.stop()

    def set_key(
        self,
        key: str,
        value: bytes,
        version: int = 1,
        ttl: int = TTL_INFINITY,
        area: str = DEFAULT_AREA,
        originator: Optional[str] = None,
    ) -> None:
        originator = originator or self.node_id
        self.store.set_key_vals(
            area,
            KeySetParams(
                key_vals={
                    key: Value(
                        version=version,
                        originator_id=originator,
                        value=value,
                        ttl=ttl,
                        hash=wire.generate_hash(version, originator, value),
                    )
                },
                originator_id=originator,
            ),
        )

    def get_key(self, key: str, area: str = DEFAULT_AREA) -> Optional[Value]:
        return self.store.get_key_vals(area, [key]).get(key)

    def dump(self, area: str = DEFAULT_AREA) -> Dict[str, Value]:
        return self.store.dump_with_filters(area).key_vals

    def add_peer(self, other: "KvStoreWrapper", area: str = DEFAULT_AREA) -> None:
        self.store.add_peer(
            area, other.node_id, InProcessTransport(other.store)
        )

    def del_peer(self, other_name: str, area: str = DEFAULT_AREA) -> None:
        self.store.del_peer(area, other_name)

    def recv_publication(self, timeout: float = 5.0) -> Publication:
        return self._reader.get(timeout=timeout)

    def peer_states(self, area: str = DEFAULT_AREA):
        return self.store.peer_states(area)


def link_bidirectional(a: KvStoreWrapper, b: KvStoreWrapper, area=DEFAULT_AREA):
    a.add_peer(b, area)
    b.add_peer(a, area)
