"""Host-side link-state graph engine.

Behavioral parity with the reference ``openr/decision/LinkState.{h,cpp}``:

- only *bidirectional* links exist (both ends advertise the adjacency,
  matched on interface names; reference: LinkState.cpp:532 maybeMakeLink)
- per-direction metric / overload with hold-down semantics for ordered-FIB
  programming (RFC 6976 style; reference: LinkState.h:24 HoldableValue)
- incremental adjacency-database merge with topology-change detection
  (reference: LinkState.cpp:565 updateAdjacencyDatabase)
- memoized shortest-paths results invalidated on topology change
  (reference: LinkState.cpp:794 getSpfResult)
- k-edge-disjoint path enumeration via iterative SPF with link exclusion
  (reference: LinkState.cpp:763 getKthPaths, :399 traceOnePath)

This class is the system of record on the host. The TPU compute path does
not walk this object graph: ``openr_tpu.graph.snapshot`` compiles it into
dense device arrays and ``openr_tpu.ops.spf`` recomputes shortest paths
algebraically. The Dijkstra here is retained as (a) the small-topology /
no-accelerator fallback and (b) the golden oracle the kernels are fuzzed
against.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from openr_tpu.analysis.annotations import thread_confined
from openr_tpu.types import Adjacency, AdjacencyDatabase, BinaryAddress

Metric = int


_NO_HOLD = object()


class HoldableValue:
    """A value whose previous state can be *held* for a TTL when it changes.

    Used for ordered FIB programming: an improving change (metric decrease,
    overload clear) is held for ``hold_up_ttl`` ticks, a degrading change for
    ``hold_down_ttl``. reference: LinkState.h:24-58, LinkState.cpp:53-120.
    """

    __slots__ = ("_val", "_held", "_hold_ttl", "_is_bool")

    def __init__(self, val):
        self._val = val
        self._held = _NO_HOLD
        self._hold_ttl = 0
        self._is_bool = isinstance(val, bool)

    @property
    def value(self):
        return self._val if self._held is _NO_HOLD else self._held

    @property
    def raw(self):
        return self._val

    def has_hold(self) -> bool:
        return self._held is not _NO_HOLD

    def set(self, val) -> None:
        self._val = val
        self._held = _NO_HOLD
        self._hold_ttl = 0

    def _is_change_bringing_up(self, val) -> bool:
        if self._is_bool:
            return self._val and not val  # overload clearing == up
        return val < self._val  # metric decrease == up

    def update_value(self, val, hold_up_ttl: int, hold_down_ttl: int) -> bool:
        """Returns True iff the *observable* value changed now."""
        if val == self._val:
            return False
        if self.has_hold():
            # a second change while holding: drop the hold, apply fast
            self._held = _NO_HOLD
            self._hold_ttl = 0
        else:
            self._hold_ttl = (
                hold_up_ttl if self._is_change_bringing_up(val) else hold_down_ttl
            )
            if self._hold_ttl != 0:
                self._held = self._val
        self._val = val
        return not self.has_hold()

    def decrement_ttl(self) -> bool:
        if self.has_hold():
            self._hold_ttl -= 1
            if self._hold_ttl == 0:
                self._held = _NO_HOLD
                return True
        return False


class Link:
    """One bidirectional link, addressable from either end node.

    Identity: the unordered pair of (node, iface) ordered pairs
    (reference: LinkState.h:82 Link, orderedNames_).
    """

    __slots__ = (
        "area",
        "n1",
        "n2",
        "if1",
        "if2",
        "_metric1",
        "_metric2",
        "_overload1",
        "_overload2",
        "adj_label1",
        "adj_label2",
        "nh_v4_1",
        "nh_v4_2",
        "nh_v6_1",
        "nh_v6_2",
        "hold_up_ttl",
        "ordered_names",
        "_hash",
    )

    def __init__(
        self,
        area: str,
        node1: str,
        adj1: Adjacency,
        node2: str,
        adj2: Adjacency,
    ):
        self.area = area
        self.n1 = node1
        self.n2 = node2
        self.if1 = adj1.if_name
        self.if2 = adj2.if_name
        self._metric1 = HoldableValue(int(adj1.metric))
        self._metric2 = HoldableValue(int(adj2.metric))
        self._overload1 = HoldableValue(bool(adj1.is_overloaded))
        self._overload2 = HoldableValue(bool(adj2.is_overloaded))
        self.adj_label1 = adj1.adj_label
        self.adj_label2 = adj2.adj_label
        self.nh_v4_1 = adj1.next_hop_v4
        self.nh_v4_2 = adj2.next_hop_v4
        self.nh_v6_1 = adj1.next_hop_v6
        self.nh_v6_2 = adj2.next_hop_v6
        self.hold_up_ttl = 0
        self.ordered_names = tuple(
            sorted(((self.n1, self.if1), (self.n2, self.if2)))
        )
        # identity hash, cached: links land in sets/dicts on the KSP2
        # trace hot path (hundreds of thousands of hashes per churn
        # event network-wide) and the tuple-of-tuples hash is not free
        self._hash = hash(self.ordered_names)

    # -- identity ---------------------------------------------------------

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Link) and self.ordered_names == other.ordered_names
        )

    def __lt__(self, other: "Link") -> bool:
        return self.ordered_names < other.ordered_names

    def __repr__(self) -> str:
        return (
            f"Link({self.area} - {self.n1}%{self.if1} <---> "
            f"{self.n2}%{self.if2})"
        )

    # -- directional accessors -------------------------------------------

    def _dir(self, node: str) -> int:
        if node == self.n1:
            return 1
        if node == self.n2:
            return 2
        raise KeyError(node)

    def other_node(self, node: str) -> str:
        return self.n2 if self._dir(node) == 1 else self.n1

    def iface_from(self, node: str) -> str:
        return self.if1 if self._dir(node) == 1 else self.if2

    def metric_from(self, node: str) -> Metric:
        return (self._metric1 if self._dir(node) == 1 else self._metric2).value

    def metric_and_other(self, node: str) -> Tuple[Metric, str]:
        """Fused (metric_from, other_node) for path-walk hot loops
        (KSP2 backtrace accumulates these per hop)."""
        if node == self.n1:
            return self._metric1.value, self.n2
        if node == self.n2:
            return self._metric2.value, self.n1
        raise KeyError(node)

    def overload_from(self, node: str) -> bool:
        return (
            self._overload1 if self._dir(node) == 1 else self._overload2
        ).value

    def metric_raw_from(self, node: str) -> Metric:
        """The ADVERTISED metric, ignoring any active hold — what merge
        guards must compare against: a revert advertisement during a
        hold would otherwise never reach the HoldableValue and the
        held-away value would become visible at expiry."""
        return (self._metric1 if self._dir(node) == 1 else self._metric2).raw

    def overload_raw_from(self, node: str) -> bool:
        return (
            self._overload1 if self._dir(node) == 1 else self._overload2
        ).raw

    def adj_label_from(self, node: str) -> int:
        return self.adj_label1 if self._dir(node) == 1 else self.adj_label2

    def nh_v4_from(self, node: str) -> BinaryAddress:
        return self.nh_v4_1 if self._dir(node) == 1 else self.nh_v4_2

    def nh_v6_from(self, node: str) -> BinaryAddress:
        return self.nh_v6_1 if self._dir(node) == 1 else self.nh_v6_2

    # -- mutation (returns True when topology-visible value changed) ------

    def set_metric_from(
        self, node: str, m: Metric, hold_up: int = 0, hold_down: int = 0
    ) -> bool:
        hv = self._metric1 if self._dir(node) == 1 else self._metric2
        return hv.update_value(int(m), hold_up, hold_down)

    def set_overload_from(
        self, node: str, overloaded: bool, hold_up: int = 0, hold_down: int = 0
    ) -> bool:
        was_up = self.is_up()
        hv = self._overload1 if self._dir(node) == 1 else self._overload2
        hv.update_value(bool(overloaded), hold_up, hold_down)
        # simplex overload not supported: only a change in is_up() is a
        # topology change (reference: LinkState.cpp:344 setOverloadFromNode)
        return was_up != self.is_up()

    def set_adj_label_from(self, node: str, label: int) -> None:
        if self._dir(node) == 1:
            self.adj_label1 = label
        else:
            self.adj_label2 = label

    def set_nh_v4_from(self, node: str, nh: BinaryAddress) -> None:
        if self._dir(node) == 1:
            self.nh_v4_1 = nh
        else:
            self.nh_v4_2 = nh

    def set_nh_v6_from(self, node: str, nh: BinaryAddress) -> None:
        if self._dir(node) == 1:
            self.nh_v6_1 = nh
        else:
            self.nh_v6_2 = nh

    # -- state ------------------------------------------------------------

    def is_up(self) -> bool:
        """Up iff no hold-up countdown pending and neither direction is
        overloaded (reference: LinkState.cpp:236 Link::isUp)."""
        return (
            self.hold_up_ttl == 0
            and not self._overload1.value
            and not self._overload2.value
        )

    def set_hold_up_ttl(self, ttl: int) -> None:
        self.hold_up_ttl = ttl

    def decrement_holds(self) -> bool:
        expired = False
        if self.hold_up_ttl != 0:
            self.hold_up_ttl -= 1
            expired |= self.hold_up_ttl == 0
        expired |= self._metric1.decrement_ttl()
        expired |= self._metric2.decrement_ttl()
        expired |= self._overload1.decrement_ttl()
        expired |= self._overload2.decrement_ttl()
        return expired

    def has_holds(self) -> bool:
        return (
            self.hold_up_ttl != 0
            or self._metric1.has_hold()
            or self._metric2.has_hold()
            or self._overload1.has_hold()
            or self._overload2.has_hold()
        )


@dataclass
class LinkStateChange:
    """What an update did to the graph (reference: LinkState.h:307)."""

    topology_changed: bool = False
    link_attributes_changed: bool = False
    node_label_changed: bool = False

    def __or__(self, other: "LinkStateChange") -> "LinkStateChange":
        return LinkStateChange(
            self.topology_changed or other.topology_changed,
            self.link_attributes_changed or other.link_attributes_changed,
            self.node_label_changed or other.node_label_changed,
        )


class NodeSpfResult:
    """Shortest-path result for one destination node: metric, first-hop
    (ECMP) node set, and predecessor links for path backtracing.
    reference: LinkState.h:203 NodeSpfResult."""

    __slots__ = ("metric", "next_hops", "path_links", "_links_sorted")

    def __init__(self, metric: Metric):
        self.metric = metric
        self.next_hops: Set[str] = set()
        # (link, prev_node) pairs: incoming shortest-path edges
        self.path_links: List[Tuple[Link, str]] = []
        self._links_sorted = False

    def sorted_path_links(self) -> List[Tuple[Link, str]]:
        """Canonical-order predecessor links, sorted once per node (the
        trace backtracks, so per-visit sorting would repeat the work)."""
        if not self._links_sorted:
            self.path_links.sort(key=lambda lp: lp[0].ordered_names)
            self._links_sorted = True
        return self.path_links

    def reset(self, metric: Metric) -> None:
        self.metric = metric
        self.next_hops = set()
        self.path_links = []
        self._links_sorted = False

    def __repr__(self) -> str:
        return f"NodeSpfResult(m={self.metric}, nh={sorted(self.next_hops)})"


SpfResult = Dict[str, NodeSpfResult]
Path = List[Link]


# externally serialized, never internally locked: every LinkState is
# created and driven by exactly one plane — Decision's under evb, a
# ctrl handler's (tenant mirrors, replica absorb, warm import) under
# SolverCtrlHandler._lock, the twin's on its one thread. The
# shared-state rule merges all instances by class, so cross-role
# access to one instance is impossible by construction — hence
# "owner" confinement (same contract as WorldManager).
@thread_confined(
    "owner",
    "_adj_dbs",
    "_kth_path_cache",
    "_link_map",
    "_node_overloads",
    "_ordered_links_memo",
    "_spf_cache",
    "attr_journal",
    "attributes_version",
    "change_journal",
    "topology_version",
)
class LinkState:
    """Area-scoped link-state graph with incremental updates and memoized
    shortest-path queries."""

    def __init__(self, area: str = "0"):
        self.area = area
        self._link_map: Dict[str, Set[Link]] = {}
        self._all_links: Set[Link] = set()
        self._node_overloads: Dict[str, HoldableValue] = {}
        self._adj_dbs: Dict[str, AdjacencyDatabase] = {}
        self._spf_cache: Dict[Tuple[str, bool], SpfResult] = {}
        # per-node canonical link order, valid for one topology version
        self._ordered_links_memo: Dict[str, List[Link]] = {}
        self._kth_path_cache: Dict[Tuple[str, str, int], List[Path]] = {}
        # monotonically bumped on every topology change; the device snapshot
        # layer keys HBM-resident arrays off this (replaces the reference's
        # SPF memo invalidation for the device path)
        self.topology_version = 0
        # journal of (version, affected nodes) per topology change so the
        # snapshot layer can patch only touched rows instead of a full
        # rebuild; bounded ring — evicted history forces a full recompile
        from collections import deque

        self.change_journal = deque(maxlen=4096)
        # attribute changes (node labels, adj labels, next-hop addresses,
        # interface identities) do NOT move distances, so they bump a
        # separate version: SPF memos and device snapshots stay valid,
        # while route-materialization caches (the incremental KSP2
        # engine's) can still detect and re-derive affected routes
        # (reference keeps the same split: LinkStateChange
        # topologyChanged vs linkAttributesChanged)
        self.attributes_version = 0
        self.attr_journal = deque(maxlen=4096)

    # -- introspection ----------------------------------------------------

    def has_node(self, node: str) -> bool:
        return node in self._adj_dbs

    def nodes(self) -> List[str]:
        return sorted(self._link_map)

    @property
    def num_links(self) -> int:
        return len(self._all_links)

    @property
    def num_nodes(self) -> int:
        return len(self._link_map)

    def links_from_node(self, node: str) -> Set[Link]:
        return self._link_map.get(node, set())

    def ordered_links_from_node(self, node: str) -> List[Link]:
        """Node's links in canonical order. Memoized per topology
        version (link IDENTITY is immutable, so attribute churn never
        reorders; membership changes invalidate via _invalidate) — the
        churn hot path sorts the same high-degree node repeatedly
        within one rebuild. Callers must not mutate the list."""
        cached = self._ordered_links_memo.get(node)
        if cached is None:
            cached = sorted(self._link_map.get(node, set()))
            self._ordered_links_memo[node] = cached
        return cached

    def all_links(self) -> Set[Link]:
        return self._all_links

    def is_node_overloaded(self, node: str) -> bool:
        hv = self._node_overloads.get(node)
        return bool(hv.value) if hv is not None else False

    def get_adjacency_databases(self) -> Dict[str, AdjacencyDatabase]:
        return self._adj_dbs

    def has_holds(self) -> bool:
        return any(l.has_holds() for l in self._all_links) or any(
            hv.has_hold() for hv in self._node_overloads.values()
        )

    # -- mutation ---------------------------------------------------------

    def _invalidate(self, affected: Optional[Set[str]] = None) -> None:
        self._spf_cache.clear()
        self._kth_path_cache.clear()
        self._ordered_links_memo.clear()
        self.topology_version += 1
        self.change_journal.append(
            (self.topology_version, frozenset(affected or ()))
        )

    def affected_since(self, version: int) -> Optional[Set[str]]:
        """Union of nodes touched by all changes after ``version``; None if
        the journal can't prove coverage (forces a full recompile)."""
        return self._affected_since(
            self.change_journal, self.topology_version, version
        )

    def attr_affected_since(self, version: int) -> Optional[Set[str]]:
        """Like affected_since, over the attribute-change journal."""
        return self._affected_since(
            self.attr_journal, self.attributes_version, version
        )

    @staticmethod
    def _affected_since(journal, current: int, version: int):
        if version == current:
            return set()
        if not journal or journal[0][0] > version + 1:
            return None  # history evicted: coverage unknown
        affected: Set[str] = set()
        for v, nodes in journal:
            if v <= version:
                continue
            if not nodes:
                return None  # a change with unrecorded blast radius
            affected |= nodes
        return affected

    def _note_attr_change(self, affected: Set[str]) -> None:
        self.attributes_version += 1
        self.attr_journal.append(
            (self.attributes_version, frozenset(affected))
        )

    def _maybe_make_link(self, node: str, adj: Adjacency) -> Optional[Link]:
        """Create a Link only if the reverse adjacency is also advertised
        (reference: LinkState.cpp:532 maybeMakeLink)."""
        other_db = self._adj_dbs.get(adj.other_node_name)
        if other_db is None:
            return None
        for other_adj in other_db.adjacencies:
            if (
                other_adj.other_node_name == node
                and adj.other_if_name == other_adj.if_name
                and adj.if_name == other_adj.other_if_name
            ):
                return Link(self.area, node, adj, adj.other_node_name, other_adj)
        return None

    def _ordered_link_set(self, adj_db: AdjacencyDatabase) -> List[Link]:
        links = []
        for adj in adj_db.adjacencies:
            link = self._maybe_make_link(adj_db.this_node_name, adj)
            if link is not None:
                links.append(link)
        links.sort()
        return links

    def _add_link(self, link: Link) -> None:
        self._link_map.setdefault(link.n1, set()).add(link)
        self._link_map.setdefault(link.n2, set()).add(link)
        self._all_links.add(link)
        # membership can change WITHOUT _invalidate (a held-down add or
        # a removal of a down link leaves topology_changed False): the
        # order memo must drop the endpoints here, not only on
        # invalidation (code-review repro: a held A-C add followed by a
        # metric update misread the stale memo as 'new link' and lost
        # the update)
        self._ordered_links_memo.pop(link.n1, None)
        self._ordered_links_memo.pop(link.n2, None)

    def _remove_link(self, link: Link) -> None:
        self._link_map[link.n1].discard(link)
        self._link_map[link.n2].discard(link)
        self._all_links.discard(link)
        self._ordered_links_memo.pop(link.n1, None)
        self._ordered_links_memo.pop(link.n2, None)

    def _remove_node(self, node: str) -> None:
        for link in list(self._link_map.get(node, ())):
            other = link.other_node(node)
            self._link_map[other].discard(link)
            self._all_links.discard(link)
            self._ordered_links_memo.pop(other, None)
        self._link_map.pop(node, None)
        self._ordered_links_memo.pop(node, None)
        self._node_overloads.pop(node, None)

    def _update_node_overloaded(
        self, node: str, overloaded: bool, hold_up: int, hold_down: int
    ) -> bool:
        hv = self._node_overloads.get(node)
        if hv is not None:
            return hv.update_value(bool(overloaded), hold_up, hold_down)
        self._node_overloads[node] = HoldableValue(bool(overloaded))
        # a brand-new node's initial overload state is not a "change"
        return False

    def update_adjacency_database(
        self,
        adj_db: AdjacencyDatabase,
        hold_up_ttl: int = 0,
        hold_down_ttl: int = 0,
    ) -> LinkStateChange:
        """Incrementally merge one node's new adjacency database.

        Walks the old and new ordered link sets in lockstep to discover
        adds / removes / in-place attribute changes.
        reference: LinkState.cpp:565-719 updateAdjacencyDatabase.
        """
        change = LinkStateChange()
        node = adj_db.this_node_name
        assert adj_db.area == self.area, (adj_db.area, self.area)

        prior_db = self._adj_dbs.get(node)
        self._adj_dbs[node] = adj_db

        old_links = self.ordered_links_from_node(node)
        new_links = self._ordered_link_set(adj_db)

        change.topology_changed |= self._update_node_overloaded(
            node, adj_db.is_overloaded, hold_up_ttl, hold_down_ttl
        )
        change.node_label_changed = (
            prior_db is None and adj_db.node_label != 0
        ) or (prior_db is not None and prior_db.node_label != adj_db.node_label)

        # blast radius: the node itself plus peers of links that
        # ACTUALLY changed — not every peer. Journal consumers patch
        # per-node device rows (snapshot / ELL bands), so a coarse set
        # re-derived ~17 high-degree rows per single-adjacency metric
        # wiggle at 100k where 2 suffice. Held changes are excluded
        # here and journaled by decrement_holds at expiry, which
        # already records the expired links' endpoints.
        affected = {node}
        attr_affected = {node}

        oi, ni = 0, 0
        while ni < len(new_links) or oi < len(old_links):
            if ni < len(new_links) and (
                oi >= len(old_links) or new_links[ni] < old_links[oi]
            ):
                # new link coming up
                new_links[ni].set_hold_up_ttl(hold_up_ttl)
                change.topology_changed |= new_links[ni].is_up()
                affected.add(new_links[ni].other_node(node))
                self._add_link(new_links[ni])
                ni += 1
                continue
            if oi < len(old_links) and (
                ni >= len(new_links) or old_links[oi] < new_links[ni]
            ):
                # old link going away; if it was held or overloaded this is
                # not a visible topology change
                change.topology_changed |= old_links[oi].is_up()
                affected.add(old_links[oi].other_node(node))
                self._remove_link(old_links[oi])
                oi += 1
                continue
            new, old = new_links[ni], old_links[oi]
            # compare against the RAW (advertised) value, not the
            # observable one: during a hold those differ, and a revert
            # advertisement must reach the HoldableValue (which drops
            # the hold and applies fast) instead of silently letting
            # the held-away value win at expiry (code-review repro)
            if new.metric_from(node) != old.metric_raw_from(node):
                if old.set_metric_from(
                    node, new.metric_from(node), hold_up_ttl, hold_down_ttl
                ):
                    change.topology_changed = True
                    affected.add(old.other_node(node))
            if new.overload_from(node) != old.overload_raw_from(node):
                if old.set_overload_from(
                    node, new.overload_from(node), hold_up_ttl, hold_down_ttl
                ):
                    change.topology_changed = True
                    affected.add(old.other_node(node))
            if new.adj_label_from(node) != old.adj_label_from(node):
                change.link_attributes_changed = True
                attr_affected.add(old.other_node(node))
                old.set_adj_label_from(node, new.adj_label_from(node))
            if new.nh_v4_from(node) != old.nh_v4_from(node):
                change.link_attributes_changed = True
                attr_affected.add(old.other_node(node))
                old.set_nh_v4_from(node, new.nh_v4_from(node))
            if new.nh_v6_from(node) != old.nh_v6_from(node):
                change.link_attributes_changed = True
                attr_affected.add(old.other_node(node))
                old.set_nh_v6_from(node, new.nh_v6_from(node))
            ni += 1
            oi += 1

        if change.topology_changed:
            self._invalidate(affected)
        if change.link_attributes_changed or change.node_label_changed:
            self._note_attr_change(attr_affected)
        return change

    def delete_adjacency_database(self, node: str) -> LinkStateChange:
        """reference: LinkState.cpp:722 deleteAdjacencyDatabase"""
        change = LinkStateChange()
        if node in self._adj_dbs:
            affected = {node}
            affected.update(
                l.other_node(node) for l in self._link_map.get(node, ())
            )
            self._remove_node(node)
            del self._adj_dbs[node]
            self._invalidate(affected)
            change.topology_changed = True
        return change

    def decrement_holds(self) -> LinkStateChange:
        """One ordered-FIB tick: age all holds; expiry is a topology change.
        reference: LinkState.cpp:501 decrementHolds."""
        change = LinkStateChange()
        affected: Set[str] = set()
        for link in self._all_links:
            if link.decrement_holds():
                change.topology_changed = True
                affected.add(link.n1)
                affected.add(link.n2)
        for node, hv in self._node_overloads.items():
            if hv.decrement_ttl():
                change.topology_changed = True
                affected.add(node)
        if change.topology_changed:
            self._invalidate(affected)
        return change

    # -- shortest paths (host oracle / fallback) --------------------------

    def get_spf_result(
        self, node: str, use_link_metric: bool = True
    ) -> SpfResult:
        """Memoized single-source shortest paths (reference:
        LinkState.cpp:794 getSpfResult)."""
        key = (node, use_link_metric)
        cached = self._spf_cache.get(key)
        if cached is None:
            cached = self.run_spf(node, use_link_metric)
            self._spf_cache[key] = cached
        return cached

    def run_spf(
        self,
        src: str,
        use_link_metric: bool = True,
        links_to_ignore: Optional[Set[Link]] = None,
    ) -> SpfResult:
        """Dijkstra with ECMP first-hop accumulation and overloaded-node
        transit exclusion (reference: LinkState.cpp:809-882 runSpf).

        First-hop semantics: a destination's ``next_hops`` is the set of the
        source's neighbor *node names* lying on any equal-cost shortest
        path; a directly-connected destination contributes itself.
        """
        ignore = links_to_ignore or set()
        result: SpfResult = {}
        pending: Dict[str, NodeSpfResult] = {src: NodeSpfResult(0)}
        heap: List[Tuple[Metric, str]] = [(0, src)]
        while heap:
            metric, u = heapq.heappop(heap)
            node_res = pending.get(u)
            if node_res is None or node_res.metric != metric:
                continue  # stale heap entry
            del pending[u]
            result[u] = node_res
            if u != src and self.is_node_overloaded(u):
                # no transit through overloaded nodes: record reachability
                # but do not relax its adjacencies
                continue
            for link in self._link_map.get(u, ()):  # unordered, like the ref
                v = link.other_node(u)
                if not link.is_up() or v in result or link in ignore:
                    continue
                m = link.metric_from(u) if use_link_metric else 1
                cand = node_res.metric + m
                v_res = pending.get(v)
                if v_res is None:
                    v_res = pending[v] = NodeSpfResult(cand)
                    heapq.heappush(heap, (cand, v))
                if v_res.metric >= cand:
                    if v_res.metric > cand:
                        v_res.reset(cand)
                        heapq.heappush(heap, (cand, v))
                    v_res.path_links.append((link, u))
                    v_res.next_hops |= node_res.next_hops
                    if not v_res.next_hops:
                        v_res.next_hops.add(v)  # directly connected
        return result

    def get_metric_from_a_to_b(
        self, a: str, b: str, use_link_metric: bool = True
    ) -> Optional[Metric]:
        if a == b:
            return 0
        res = self.get_spf_result(a, use_link_metric)
        return res[b].metric if b in res else None

    def get_hops_from_a_to_b(self, a: str, b: str) -> Optional[Metric]:
        return self.get_metric_from_a_to_b(a, b, use_link_metric=False)

    def get_max_hops_to_node(self, node: str) -> Metric:
        return max(
            (r.metric for r in self.get_spf_result(node, False).values()),
            default=0,
        )

    # -- k edge-disjoint paths -------------------------------------------

    def _trace_one_path(
        self,
        src: str,
        dest: str,
        result: SpfResult,
        links_to_ignore: Set[Link],
    ) -> Optional[Path]:
        """Walk predecessor links dest -> src, consuming each link at most
        once across calls (reference: LinkState.cpp:399 traceOnePath).

        Candidates are visited in canonical (sorted) link order — the
        reference iterates an unordered container, so any fixed order is
        spec-conformant, and a DETERMINISTIC one lets the device-assisted
        KSP2 path (solver _prefetch_ksp2_paths) reproduce identical
        traces from masked distance rows."""
        if src == dest:
            return []
        for link, prev in result[dest].sorted_path_links():
            if link in links_to_ignore:
                continue
            links_to_ignore.add(link)
            sub = self._trace_one_path(src, prev, result, links_to_ignore)
            if sub is not None:
                sub.append(link)
                return sub
        return None

    def prime_kth_paths(
        self, src: str, dest: str, k: int, paths: List[Path]
    ) -> None:
        """Seed the kth-path cache with externally computed paths (the
        solver's device-batched masked-SPF KSP2 prefetch); entries are
        dropped with the cache on any topology change."""
        self._kth_path_cache[(src, dest, k)] = paths

    def parallel_pairs(self) -> Set[FrozenSet[str]]:
        """Node pairs connected by more than one (parallel) link."""
        counts: Dict[FrozenSet[str], int] = {}
        for link in self.all_links():
            pair = frozenset((link.n1, link.n2))
            counts[pair] = counts.get(pair, 0) + 1
        return {pair for pair, c in counts.items() if c > 1}

    def get_kth_paths(self, src: str, dest: str, k: int) -> List[Path]:
        """Edge-disjoint paths of rank k: SPF excluding all links used by
        ranks < k, then enumerate link-disjoint traces.
        reference: LinkState.cpp:763 getKthPaths."""
        assert k >= 1
        key = (src, dest, k)
        cached = self._kth_path_cache.get(key)
        if cached is not None:
            return cached
        links_to_ignore: Set[Link] = set()
        for i in range(1, k):
            for path in self.get_kth_paths(src, dest, i):
                links_to_ignore.update(path)
        paths: List[Path] = []
        res = (
            self.get_spf_result(src, True)
            if not links_to_ignore
            else self.run_spf(src, True, links_to_ignore)
        )
        if dest in res:
            visited: Set[Link] = set()
            path = self._trace_one_path(src, dest, res, visited)
            while path:
                paths.append(path)
                path = self._trace_one_path(src, dest, res, visited)
        self._kth_path_cache[key] = paths
        return paths

    @staticmethod
    def path_a_in_path_b(a: Path, b: Path) -> bool:
        """True if path a appears as a contiguous subsequence of path b.
        reference: LinkState.h:396 pathAInPathB."""
        if len(a) > len(b):
            return False
        for i in range(len(b) - len(a) + 1):
            if all(a[j] == b[i + j] for j in range(len(a))):
                return True
        return False
