"""LinkState -> device-array snapshot compiler.

The TPU compute path never walks the host object graph. Instead, each
topology version of a ``LinkState`` is *compiled* once into dense arrays:

- node-name interning: sorted names -> dense ids (stable for a given node
  set, so unchanged topologies reuse the resident snapshot)
- ``metric[N, N]`` int32 directed min-metric matrix (INF where no up link;
  min over parallel links per direction)
- ``overloaded[N]`` node transit-exclusion mask
- directed-link metadata (iface, addrs, labels) kept host-side for
  next-hop materialization

This replaces the reference's per-(source, useLinkMetric) SPF memo cache
(reference: openr/decision/LinkState.cpp:794-803): the memo key here is
``LinkState.topology_version`` and the cached artifact is the HBM-resident
metric matrix, against which any batch of sources can be solved.

Padding: N is padded up to the next multiple of 128 (TPU lane width) so
recompilation only happens when the node count crosses a bucket boundary,
not on every node join/leave.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from openr_tpu.graph.linkstate import Link, LinkState

# Distance/metric infinity sentinel. Chosen so that INF + INF still fits
# in int32 (no wraparound in the relaxation adds): 2**30 - 1, and
# 2*(2**30 - 1) == 2**31 - 2 < 2**31 - 1.
INF = np.int32((1 << 30) - 1)

_PAD = 128


def _padded(n: int) -> int:
    return max(_PAD, ((n + _PAD - 1) // _PAD) * _PAD)


@dataclass
class DirectedLink:
    """Host-side metadata for one direction of one up link; indexed
    parallel to the snapshot's directed-link arrays."""

    link: Link
    src: str
    dst: str
    src_id: int
    dst_id: int
    metric: int


@dataclass
class GraphSnapshot:
    area: str
    version: int
    node_names: List[str]  # index == dense node id
    node_index: Dict[str, int]
    n: int  # real node count
    n_pad: int  # padded node count (metric matrix dimension)
    metric: np.ndarray  # [n_pad, n_pad] int32, INF where no edge
    hop: np.ndarray  # [n_pad, n_pad] int32, 1 where edge, INF elsewhere
    overloaded: np.ndarray  # [n_pad] bool
    directed_links: List[DirectedLink]
    # per node id: indices into directed_links of links leaving that node
    links_from: List[List[int]]

    def id_of(self, node: str) -> Optional[int]:
        return self.node_index.get(node)


def compile_snapshot(ls: LinkState) -> GraphSnapshot:
    """Compile the current LinkState topology into a GraphSnapshot."""
    names = sorted(ls.get_adjacency_databases().keys())
    index = {name: i for i, name in enumerate(names)}
    n = len(names)
    n_pad = _padded(n)

    metric = np.full((n_pad, n_pad), INF, dtype=np.int32)
    overloaded = np.zeros((n_pad,), dtype=bool)
    directed: List[DirectedLink] = []
    links_from: List[List[int]] = [[] for _ in range(n)]

    for name in names:
        i = index[name]
        overloaded[i] = ls.is_node_overloaded(name)
        for link in ls.ordered_links_from_node(name):
            if not link.is_up():
                continue
            j = index[link.other_node(name)]
            m = min(int(link.metric_from(name)), int(INF) - 1)
            links_from[i].append(len(directed))
            directed.append(
                DirectedLink(
                    link=link,
                    src=name,
                    dst=link.other_node(name),
                    src_id=i,
                    dst_id=j,
                    metric=m,
                )
            )
            if m < metric[i, j]:
                metric[i, j] = m

    hop = np.where(metric < INF, np.int32(1), INF).astype(np.int32)
    return GraphSnapshot(
        area=ls.area,
        version=ls.topology_version,
        node_names=names,
        node_index=index,
        n=n,
        n_pad=n_pad,
        metric=metric,
        hop=hop,
        overloaded=overloaded,
        directed_links=directed,
        links_from=links_from,
    )


class SnapshotCache:
    """Versioned snapshot cache keyed by LinkState *identity* (weakly held)
    so distinct graphs never alias, plus topology_version for staleness."""

    def __init__(self) -> None:
        import weakref

        self._cache: "weakref.WeakKeyDictionary[LinkState, GraphSnapshot]" = (
            weakref.WeakKeyDictionary()
        )

    def get(self, ls: LinkState) -> GraphSnapshot:
        snap = self._cache.get(ls)
        if snap is None or snap.version != ls.topology_version:
            snap = compile_snapshot(ls)
            self._cache[ls] = snap
        return snap

    def invalidate(self) -> None:
        self._cache.clear()
