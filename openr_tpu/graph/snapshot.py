"""LinkState -> device-array snapshot compiler, with incremental patching.

The TPU compute path never walks the host object graph. Each topology
version of a ``LinkState`` is *compiled* into dense arrays:

- node-name interning: sorted names -> dense ids (stable for a given node
  set, so unchanged topologies reuse the resident snapshot)
- ``metric[N, N]`` int32 directed min-metric matrix (INF where no up link;
  min over parallel links per direction)
- ``overloaded[N]`` node transit-exclusion mask
- per-source-node directed-link metadata for next-hop materialization

This replaces the reference's per-(source, useLinkMetric) SPF memo cache
(reference: openr/decision/LinkState.cpp:794-803): the memo key is
``LinkState.topology_version`` and the cached artifact is the HBM-resident
metric matrix, against which any batch of sources is solved.

Incremental path: LinkState journals the affected nodes of every topology
change. When the node set is unchanged, a new snapshot is produced by
*patching* only the affected rows — and the device copy is updated with a
row-scatter instead of re-uploading the whole matrix, so the steady-state
churn cost is O(changed rows), not O(N^2). The hop-count matrix is derived
from the metric matrix on device.

Padding: N is padded to the next multiple of 128 (TPU lane width) so
recompilation only happens when the node count crosses a bucket boundary.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from openr_tpu.graph.linkstate import Link, LinkState

# Distance/metric infinity sentinel. Chosen so that INF + INF still fits
# in int32 (no wraparound in the relaxation adds): 2**30 - 1, and
# 2*(2**30 - 1) == 2**31 - 2 < 2**31 - 1.
INF = np.int32((1 << 30) - 1)

_PAD = 128
# row-patch bucket sizes (jit specializes per bucket; ids are padded by
# repeating the first row, which is an idempotent scatter)
_PATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


def _padded(n: int) -> int:
    return max(_PAD, ((n + _PAD - 1) // _PAD) * _PAD)


def pad_patch_rows(rows: np.ndarray) -> Optional[np.ndarray]:
    """Pad changed-row ids up to the shared bucket sizes (jit programs
    specialize per bucket; padding repeats the first row, an idempotent
    scatter). Returns None when the change exceeds the largest bucket —
    callers should fall back to a full matrix upload instead of compiling
    ever-larger scatter programs."""
    if len(rows) > _PATCH_BUCKETS[-1]:
        return None
    bucket = next(b for b in _PATCH_BUCKETS if b >= max(1, len(rows)))
    ids = np.full(bucket, rows[0] if len(rows) else 0, dtype=np.int32)
    ids[: len(rows)] = rows
    return ids


@dataclass
class DirectedLink:
    """Host-side metadata for one direction of one up link."""

    link: Link
    src: str
    dst: str
    src_id: int
    dst_id: int
    metric: int


class _DeviceArrays:
    """Resident device arrays for one snapshot. Unpacks like the
    (metric, hop, overloaded) tuple it replaced, but the hop matrix is
    derived on first access instead of eagerly per patch."""

    __slots__ = ("metric", "overloaded", "_hop")

    def __init__(self, metric, overloaded):
        self.metric = metric
        self.overloaded = overloaded
        self._hop = None

    @property
    def hop(self):
        if self._hop is None:
            self._hop = _derive_hop(self.metric)
        return self._hop

    def __iter__(self):
        return iter((self.metric, self.hop, self.overloaded))


@dataclass
class GraphSnapshot:
    area: str
    version: int
    node_names: List[str]  # index == dense node id
    node_index: Dict[str, int]
    n: int  # real node count
    n_pad: int  # padded node count (matrix dimension)
    metric: np.ndarray  # [n_pad, n_pad] int32, INF where no edge
    overloaded: np.ndarray  # [n_pad] bool
    # per node id: directed links leaving that node
    links_from: List[List[DirectedLink]]
    _hop: Optional[np.ndarray] = None
    _dev: Optional[tuple] = None
    _parent: Optional["GraphSnapshot"] = None
    _changed_rows: Optional[np.ndarray] = None

    def id_of(self, node: str) -> Optional[int]:
        return self.node_index.get(node)

    @property
    def hop(self) -> np.ndarray:
        """Hop-count (unweighted) matrix, derived lazily."""
        if self._hop is None:
            self._hop = np.where(
                self.metric < INF, np.int32(1), INF
            ).astype(np.int32)
        return self._hop

    def patch_plan(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(changed_row_ids, changed_row_values) when this snapshot is an
        unrealized patch of a parent whose device copy the caller owns.
        Callers driving their own resident device matrix (the fused
        ``ops.spf.reconverge_step``) apply this instead of re-uploading;
        returns None for a full compile. Detaches the parent chain.

        Covers METRIC rows only: the caller must refresh its overloaded
        mask from ``self.overloaded`` on every step (an O(N) upload) —
        overload flips arrive through the same patch journal but are not
        part of the row scatter."""
        if self._parent is None or self._changed_rows is None:
            return None
        rows = self._changed_rows
        self._parent = None
        return rows, self.metric[rows, :]

    def device_arrays(self):
        """(metric, hop, overloaded) as device arrays. Patched snapshots
        update their parent's resident arrays with a row scatter. The hop
        (unweighted) matrix is derived lazily on first access — most
        consumers (route rebuilds) never touch it."""
        if self._dev is not None:
            return self._dev
        import jax.numpy as jnp

        parent = self._parent
        rows = self._changed_rows
        padded_rows = pad_patch_rows(rows) if rows is not None else None
        if (
            parent is not None
            and parent._dev is not None
            and padded_rows is not None
        ):
            p_metric = parent._dev.metric
            metric_dev = _patch_rows(
                p_metric,
                jnp.asarray(padded_rows),
                jnp.asarray(self.metric[padded_rows, :]),
            )
            overloaded_dev = jnp.asarray(self.overloaded)
        else:
            metric_dev = jnp.asarray(self.metric)
            overloaded_dev = jnp.asarray(self.overloaded)
        self._dev = _DeviceArrays(metric_dev, overloaded_dev)
        # release the parent chain: resident arrays now belong to us
        self._parent = None
        return self._dev


@functools.lru_cache(maxsize=1)
def _patch_fn():
    import jax

    @jax.jit
    def patch(m, ids, vals):
        return m.at[ids, :].set(vals)

    return patch


def _patch_rows(metric_dev, row_ids, row_vals):
    # the jitted scatter must be a process-wide singleton: a fresh jit
    # closure per call would recompile on every churn step, which is
    # catastrophic when compilation is remote
    return _patch_fn()(metric_dev, row_ids, row_vals)


@functools.lru_cache(maxsize=1)
def _hop_fn():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def derive(m):
        return jnp.where(m < INF, jnp.int32(1), INF)

    return derive


def _derive_hop(metric_dev):
    return _hop_fn()(metric_dev)


def _build_node_row(
    ls: LinkState,
    name: str,
    index: Dict[str, int],
    metric: np.ndarray,
) -> List[DirectedLink]:
    """Fill row index[name] of the metric matrix and return the node's
    directed-link metadata."""
    i = index[name]
    metric[i, :] = INF
    out: List[DirectedLink] = []
    for link in ls.ordered_links_from_node(name):
        if not link.is_up():
            continue
        dst = link.other_node(name)
        j = index.get(dst)
        if j is None:
            continue
        m = min(int(link.metric_from(name)), int(INF) - 1)
        out.append(
            DirectedLink(
                link=link, src=name, dst=dst, src_id=i, dst_id=j, metric=m
            )
        )
        if m < metric[i, j]:
            metric[i, j] = m
    return out


def compile_snapshot(ls: LinkState) -> GraphSnapshot:
    """Full compile of the current LinkState topology."""
    names = sorted(ls.get_adjacency_databases().keys())
    index = {name: i for i, name in enumerate(names)}
    n = len(names)
    n_pad = _padded(n)

    metric = np.full((n_pad, n_pad), INF, dtype=np.int32)
    overloaded = np.zeros((n_pad,), dtype=bool)
    links_from: List[List[DirectedLink]] = [[] for _ in range(n)]

    for name in names:
        i = index[name]
        overloaded[i] = ls.is_node_overloaded(name)
        links_from[i] = _build_node_row(ls, name, index, metric)

    return GraphSnapshot(
        area=ls.area,
        version=ls.topology_version,
        node_names=names,
        node_index=index,
        n=n,
        n_pad=n_pad,
        metric=metric,
        overloaded=overloaded,
        links_from=links_from,
    )


def patch_snapshot(
    prev: GraphSnapshot, ls: LinkState, affected: List[str]
) -> GraphSnapshot:
    """Produce a new snapshot by re-deriving only the affected rows.
    Caller guarantees the node set is unchanged."""
    metric = prev.metric.copy()
    overloaded = prev.overloaded.copy()
    links_from = list(prev.links_from)
    rows = []
    for name in affected:
        i = prev.node_index.get(name)
        if i is None:
            continue
        rows.append(i)
        overloaded[i] = ls.is_node_overloaded(name)
        links_from[i] = _build_node_row(ls, name, prev.node_index, metric)
    return GraphSnapshot(
        area=ls.area,
        version=ls.topology_version,
        node_names=prev.node_names,
        node_index=prev.node_index,
        n=prev.n,
        n_pad=prev.n_pad,
        metric=metric,
        overloaded=overloaded,
        links_from=links_from,
        _parent=prev,
        _changed_rows=np.asarray(sorted(rows), dtype=np.int32),
    )


class SnapshotCache:
    """Versioned snapshot cache keyed by LinkState *identity* (weakly
    held); patches incrementally when the change journal covers the gap
    and the node set is unchanged."""

    def __init__(self) -> None:
        import weakref

        self._cache: "weakref.WeakKeyDictionary[LinkState, GraphSnapshot]" = (
            weakref.WeakKeyDictionary()
        )

    def get(self, ls: LinkState) -> GraphSnapshot:
        snap = self._cache.get(ls)
        if snap is not None and snap.version == ls.topology_version:
            return snap
        snap = self._compile_or_patch(ls, snap)
        self._cache[ls] = snap
        return snap

    def _compile_or_patch(
        self, ls: LinkState, prev: Optional[GraphSnapshot]
    ) -> GraphSnapshot:
        if prev is not None:
            affected = ls.affected_since(prev.version)
            if (
                affected is not None
                and len(affected) <= max(8, prev.n // 4)
                and len(ls.get_adjacency_databases()) == prev.n
                and all(name in prev.node_index for name in affected)
            ):
                # same node set guaranteed: count matches and every
                # touched node is known
                return patch_snapshot(prev, ls, sorted(affected))
        return compile_snapshot(ls)

    def invalidate(self) -> None:
        self._cache.clear()
