"""ctypes bindings for the native SPF core (native/spfcore.cpp).

Compiles the shared library on first use (g++ available in the target
image); all callers gracefully fall back to the Python/JAX paths when the
toolchain or library is unavailable (``is_available()``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO_ROOT, "native", "spfcore.cpp")
_LIB = os.path.join(_REPO_ROOT, "native", "libspfcore.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    try:
        subprocess.run(
            [
                "g++",
                "-O3",
                "-std=c++17",
                "-shared",
                "-fPIC",
                "-pthread",
                _SRC,
                "-o",
                _LIB,
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        if not os.path.exists(_LIB) or os.path.getmtime(
            _LIB
        ) < os.path.getmtime(_SRC):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _load_failed = True
            return None
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.spf_from_sources.argtypes = [
            ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p, u8p,
            i32p, ctypes.c_int32, ctypes.c_int32, i32p,
        ]
        lib.spf_all_pairs.argtypes = [
            ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p, u8p,
            ctypes.c_int32, i32p,
        ]
        lib.spf_first_hops.argtypes = [
            ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p, u8p,
            ctypes.c_int32, i32p, i32p, u8p,
        ]
        lib.ksp2_trace_batch.argtypes = [
            ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p, i32p,
            ctypes.c_int32, u8p, ctypes.c_int32, i32p, i32p,
            ctypes.c_int32, i32p, i32p, i32p, ctypes.c_int32,
        ]
        lib.ksp2_trace_batch.restype = ctypes.c_int32
        _lib = lib
        return _lib


def is_available() -> bool:
    return _load() is not None


def _as_i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _as_u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _edge_arrays(snap) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    srcs, dsts, weights = [], [], []
    for links in snap.links_from:
        for dl in links:
            srcs.append(dl.src_id)
            dsts.append(dl.dst_id)
            weights.append(dl.metric)
    return (
        np.asarray(srcs, dtype=np.int32),
        np.asarray(dsts, dtype=np.int32),
        np.asarray(weights, dtype=np.int32),
    )


def all_pairs_distances(snap, n_threads: int = 0) -> Optional[np.ndarray]:
    """All-sources distances over a GraphSnapshot via the native core.
    Returns None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = snap.n
    srcs, dsts, weights = _edge_arrays(snap)
    overloaded = np.ascontiguousarray(
        snap.overloaded[:n].astype(np.uint8)
    )
    out = np.empty((n, n), dtype=np.int32)
    if n_threads <= 0:
        n_threads = min(16, os.cpu_count() or 1)
    lib.spf_all_pairs(
        n, len(srcs), _as_i32p(srcs), _as_i32p(dsts), _as_i32p(weights),
        _as_u8p(overloaded), n_threads, _as_i32p(out),
    )
    return out


def first_hop_matrix(
    snap, src_id: int, dist_src: np.ndarray, dist_all: np.ndarray
) -> Optional[np.ndarray]:
    lib = _load()
    if lib is None:
        return None
    n = snap.n
    srcs, dsts, weights = _edge_arrays(snap)
    overloaded = np.ascontiguousarray(snap.overloaded[:n].astype(np.uint8))
    dist_src = np.ascontiguousarray(dist_src[:n].astype(np.int32))
    dist_all = np.ascontiguousarray(dist_all[:n, :n].astype(np.int32))
    out = np.zeros((n, n), dtype=np.uint8)
    lib.spf_first_hops(
        n, len(srcs), _as_i32p(srcs), _as_i32p(dsts), _as_i32p(weights),
        _as_u8p(overloaded), src_id, _as_i32p(dist_src), _as_i32p(dist_all),
        _as_u8p(out),
    )
    return out


def trace_batch(
    n: int,
    n_links: int,
    cand_off: np.ndarray,
    cand_link: np.ndarray,
    cand_uid: np.ndarray,
    cand_w: np.ndarray,
    src: int,
    transit_blocked: np.ndarray,
    dst_ids: np.ndarray,
    rows: np.ndarray,
    shared_row: bool,
    excl_off: np.ndarray,
    excl_ids: np.ndarray,
) -> Optional[list]:
    """Batched KSP2 link-disjoint path enumeration via the native core
    (spfcore.cpp ksp2_trace_batch) — byte-identical path content and
    order to ksp2_engine.trace_paths_from_row. Returns a list (one per
    destination) of lists of link-id paths, or None when the native
    library is unavailable. The int32 output buffer grows on overflow."""
    lib = _load()
    if lib is None:
        return None
    n_dsts = len(dst_ids)
    cap = max(4096, 2 * n_links + 64 * n_dsts)
    while True:
        out = np.empty(cap, dtype=np.int32)
        wrote = lib.ksp2_trace_batch(
            n, n_links, _as_i32p(cand_off), _as_i32p(cand_link),
            _as_i32p(cand_uid), _as_i32p(cand_w), src,
            _as_u8p(transit_blocked), n_dsts, _as_i32p(dst_ids),
            _as_i32p(rows), 1 if shared_row else 0,
            _as_i32p(excl_off), _as_i32p(excl_ids), _as_i32p(out), cap,
        )
        if wrote >= 0:
            break
        cap *= 4
    result = []
    pos = 0
    for _ in range(n_dsts):
        n_paths = int(out[pos]); pos += 1
        paths = []
        for _p in range(n_paths):
            ln = int(out[pos]); pos += 1
            paths.append(out[pos : pos + ln].tolist())
            pos += ln
        result.append(paths)
    return result
