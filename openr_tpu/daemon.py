"""OpenrNode: full-daemon assembly (the reference's Main.cpp + the test
fixture OpenrWrapper, openr/tests/OpenrWrapper.h:38).

Constructs the typed queues, wires the modules
(KvStore <- LinkMonitor <- Spark; KvStore -> Decision -> Fib; PrefixManager
-> KvStore) and starts them in dependency order with reverse-order
teardown (reference: Main.cpp:269-280 queue wiring, :374-504 module
startup order, :604-654 shutdown).

Multiple OpenrNodes in one process over a MockIoProvider + in-process
KvStore transports form a complete simulated network (the reference's
OpenrSystemTest pattern).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from openr_tpu.decision.decision import Decision
from openr_tpu.fib.fib import Fib
from openr_tpu.kvstore.client import KvStoreClient
from openr_tpu.kvstore.store import InProcessTransport, KvStore, PeerTransport
from openr_tpu.linkmonitor.link_monitor import LinkMonitor
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.platform.fib_service import FibService, MockFibAgent
from openr_tpu.prefixmgr.prefix_manager import PrefixManager
from openr_tpu.spark.io_provider import IoProvider
from openr_tpu.spark.spark import Spark
from openr_tpu.types import BinaryAddress, IpPrefix, PrefixEntry, PrefixType
from openr_tpu.types.spark import SparkNeighbor
from openr_tpu.utils.eventbase import OpenrEventBase


class OpenrNode:
    """One complete openr-tpu daemon instance."""

    def __init__(
        self,
        name: str,
        io_provider: IoProvider,
        node_registry: Optional[Dict[str, "OpenrNode"]] = None,
        fib_agent: Optional[FibService] = None,
        area: str = "0",
        areas: Optional[List[str]] = None,
        interface_areas: Optional[Dict[str, str]] = None,
        v6_addr: Optional[str] = None,
        spark_config: Optional[dict] = None,
        # cross-process KvStore peering: dial a neighbor's advertised
        # peer port (reference: thrift peer clients, KvStore.cpp:1400).
        # None = in-process registry resolution (simulations/tests)
        peer_transport_factory=None,
        use_rtt_metric: bool = False,
        config_store=None,
        solver_backend: str = "device",
        # library-level default is permissive (matches Decision's ctor);
        # the config-driven daemon passes the reference default (off)
        enable_rib_policy: bool = True,
        enable_v4: bool = False,
        enable_lfa: bool = False,
        enable_ordered_fib: bool = False,
        # reference default: true (Flags.cpp:39) — matches DecisionConfig
        enable_bgp_route_programming: bool = True,
        enable_best_route_selection: bool = True,
        enable_segment_routing: bool = False,
        node_label: int = 0,
        debounce_min_s: float = 0.01,
        # reference default: 250ms ceiling (common/Flags.cpp
        # decision_debounce_max_ms); tests pass a smaller value
        debounce_max_s: float = 0.25,
        enable_flood_optimization: bool = False,
        is_flood_root: bool = False,
        flood_rate=None,  # Optional[(msgs_per_sec, burst)]
        per_prefix_keys: bool = True,
        prefix_alloc=None,  # Optional[PrefixAllocationConfig]
        netlink=None,  # address programming target for the allocator
    ):
        self.name = name
        self.area = area
        # border routers participate in several areas; interface_areas maps
        # each interface to its area (default: the node's default area)
        self.areas = list(areas) if areas else [area]
        bad_areas = set((interface_areas or {}).values()) - set(self.areas)
        if bad_areas:
            # an adjacency in an unconfigured area would form at the Spark
            # level but never enter any LSDB — a silent blackhole
            raise ValueError(
                f"interface_areas references areas {sorted(bad_areas)} "
                f"not in this node's areas {self.areas}"
            )
        if area not in self.areas:
            # unlisted interfaces fall back to the default area; it must
            # be one this node actually participates in
            raise ValueError(
                f"default area {area!r} not in this node's areas "
                f"{self.areas}"
            )
        self.registry = node_registry if node_registry is not None else {}
        self.registry[name] = self

        # -- queues (reference: Main.cpp:269-280) -------------------------
        self.neighbor_updates = ReplicateQueue(name=f"{name}:neighborUpdates")
        self.interface_updates = ReplicateQueue(name=f"{name}:interfaceUpdates")
        self.route_updates = ReplicateQueue(name=f"{name}:routeUpdates")
        self.fib_updates = ReplicateQueue(name=f"{name}:fibUpdates")
        self.prefix_updates = ReplicateQueue(name=f"{name}:prefixUpdates")
        self.static_routes = ReplicateQueue(name=f"{name}:staticRoutes")
        # event-log samples from every module -> Monitor (reference:
        # Main.cpp:280 logSampleQueue wired into KvStore, LinkMonitor,
        # Fib, PrefixAllocator; Monitor drains the reader at :390)
        self.log_sample_queue = ReplicateQueue(name=f"{name}:logSamples")

        # -- modules ------------------------------------------------------
        from openr_tpu.monitor.monitor import Monitor

        self.monitor = Monitor(name, self.log_sample_queue)
        self.kvstore = KvStore(
            node_id=name,
            areas=self.areas,
            enable_flood_optimization=enable_flood_optimization,
            is_flood_root=is_flood_root,
            flood_rate=flood_rate,
            log_sample_queue=self.log_sample_queue,
        )
        self.client_evb = OpenrEventBase(name=f"kvclient:{name}")
        self.kvstore_client = KvStoreClient(
            self.client_evb, name, self.kvstore
        )
        self.decision = Decision(
            name,
            kvstore_updates_queue=self.kvstore.updates_queue,
            route_updates_queue=self.route_updates,
            static_routes_queue=self.static_routes,
            debounce_min_s=debounce_min_s,
            debounce_max_s=debounce_max_s,
            solver_backend=solver_backend,
            enable_rib_policy=enable_rib_policy,
            enable_v4=enable_v4,
            compute_lfa_paths=enable_lfa,
            enable_ordered_fib=enable_ordered_fib,
            # BGP routes are computed either way; programming them is
            # gated (reference: enable_bgp_route_programming -> dryrun
            # marks do_not_install)
            bgp_dry_run=not enable_bgp_route_programming,
            enable_best_route_selection=enable_best_route_selection,
        )
        self.fib_agent = fib_agent or MockFibAgent()
        self.fib = Fib(
            name,
            self.fib_agent,
            self.route_updates,
            fib_updates_queue=self.fib_updates,
            kvstore_client=self.kvstore_client,
            area=area,
            log_sample_queue=self.log_sample_queue,
        )
        self.spark = Spark(
            name,
            io_provider,
            self.neighbor_updates,
            interface_updates_queue=self.interface_updates,
            area=area,
            interface_areas=interface_areas,
            v6_addr=BinaryAddress.from_str(v6_addr) if v6_addr else None,
            **(spark_config or {}),
        )
        self.link_monitor = LinkMonitor(
            name,
            neighbor_updates_queue=self.neighbor_updates,
            interface_updates_queue=self.interface_updates,
            kvstore_client=self.kvstore_client,
            kvstore=self.kvstore,
            peer_transport_factory=(
                peer_transport_factory or self._peer_transport
            ),
            config_store=config_store,
            area=area,
            areas=self.areas,
            node_label=node_label,
            enable_segment_routing=enable_segment_routing,
            use_rtt_metric=use_rtt_metric,
            log_sample_queue=self.log_sample_queue,
        )
        self.prefix_manager = PrefixManager(
            name,
            self.kvstore_client,
            prefix_updates_queue=self.prefix_updates,
            # border nodes re-originate Decision's best routes across areas
            decision_route_updates_queue=(
                self.route_updates if len(self.areas) > 1 else None
            ),
            areas=self.areas,
            per_prefix_keys=per_prefix_keys,
        )
        # automatic prefix allocation (reference: Main.cpp PrefixAllocator
        # construction gated on enable_prefix_alloc)
        self.prefix_allocator = None
        if prefix_alloc is not None and prefix_alloc.enabled:
            from openr_tpu.allocators.prefix_allocator import PrefixAllocator
            from openr_tpu.types import IpPrefix as _IpPrefix

            seed = (
                _IpPrefix.from_str(prefix_alloc.seed_prefix)
                if prefix_alloc.seed_prefix
                and not prefix_alloc.static_allocation
                else None
            )
            self.prefix_allocator = PrefixAllocator(
                name,
                self.client_evb,
                self.kvstore_client,
                self.prefix_manager,
                seed_prefix=seed,
                alloc_prefix_len=prefix_alloc.alloc_prefix_len,
                static_prefixes=(
                    {} if prefix_alloc.static_allocation else None
                ),
                netlink=(
                    netlink if prefix_alloc.set_loopback_addr else None
                ),
                loopback_if=prefix_alloc.loopback_iface,
                config_store=config_store,
                area=area,
                log_sample_queue=self.log_sample_queue,
            )
        from openr_tpu.ctrl.handler import OpenrCtrlHandler

        self.ctrl_handler = OpenrCtrlHandler(
            name,
            kvstore=self.kvstore,
            decision=self.decision,
            fib=self.fib,
            link_monitor=self.link_monitor,
            prefix_manager=self.prefix_manager,
            spark=self.spark,
            monitor=self.monitor,
        )
        self.ctrl_handler._config_store = config_store
        self.ctrl_server = None  # created on demand by start_ctrl_server
        self._started = False

    # -- peering ----------------------------------------------------------

    def _peer_transport(self, nbr: SparkNeighbor) -> Optional[PeerTransport]:
        """In-process transport resolution: look the neighbor up in the
        shared registry (the analogue of dialing its thrift port from the
        handshake's transport address)."""
        other = self.registry.get(nbr.node_name)
        if other is None:
            return None
        return InProcessTransport(other.kvstore)

    # -- lifecycle (reference startup order, Main.cpp:374-504) ------------

    def start(self) -> None:
        assert not self._started
        # telemetry first: jit compile/dispatch listeners must be live
        # before any module's first solver dispatch (idempotent, no-op
        # without jax.monitoring)
        from openr_tpu.telemetry import jax_hooks

        jax_hooks.install()
        # Monitor first: it only reads the log queue, and every other
        # module may push from its first event on (reference startup
        # order: Main.cpp:385 Monitor before KvStore)
        self.monitor.start()
        self.kvstore.start()
        self.client_evb.run_in_thread()
        self.prefix_manager.start()
        self.spark.start()
        self.link_monitor.start()
        self.decision.start()
        self.fib.start()
        # plugin hook, after all modules are live (reference:
        # Main.cpp:595-601 pluginStart with the queue endpoints)
        from openr_tpu import plugin

        if plugin.has_plugin():
            cfg = getattr(self.ctrl_handler, "_config", None)
            plugin.plugin_start(
                plugin.PluginArgs(
                    prefix_updates_queue=self.prefix_updates,
                    static_routes_queue=self.static_routes,
                    route_updates_reader=self.route_updates.get_reader(
                        f"plugin:{self.name}"
                    ),
                    config=cfg,
                    bgp_config=getattr(cfg, "bgp_config", None),
                )
            )
            self._plugin_started = True
        self._started = True

    def start_ctrl_server(self, port: int = 0, ssl_context=None) -> int:
        """Expose the ctrl API over TCP, optionally TLS (reference:
        thrift ctrl server on port 2018 with optional TLS,
        Main.cpp:587). Returns the bound port."""
        from openr_tpu.ctrl.server import CtrlServer

        self.ctrl_server = CtrlServer(
            self.ctrl_handler, port=port, ssl_context=ssl_context
        )
        self.ctrl_server.start()
        return self.ctrl_server.port

    def stop(self) -> None:
        if not self._started:
            return
        # reverse order teardown (reference: Main.cpp:604-654; pluginStop
        # first, before the queues it reads from close)
        if getattr(self, "_plugin_started", False):
            from openr_tpu import plugin

            plugin.plugin_stop()
            self._plugin_started = False
        if self.ctrl_server is not None:
            self.ctrl_server.stop()
        if self.prefix_allocator is not None:
            self.prefix_allocator.stop()
        self.fib.stop()
        self.decision.stop()
        self.link_monitor.stop()
        self.spark.stop()
        self.prefix_manager.stop()
        self.client_evb.stop()
        self.client_evb.join()
        self.kvstore.stop()
        # last, so producers are already quiet; samples still queued at
        # this instant are dropped (best-effort shutdown telemetry, like
        # the reference's logSampleQueue.close() at Main.cpp:617)
        self.monitor.stop()
        self._started = False

    # -- convenience ------------------------------------------------------

    def add_interface(self, if_name: str) -> None:
        self.spark.add_interface(if_name)

    def advertise_loopback(self, prefix_str: str, **entry_kwargs) -> IpPrefix:
        prefix = IpPrefix.from_str(prefix_str)
        self.prefix_manager.advertise_prefixes(
            [
                PrefixEntry(
                    prefix=prefix,
                    type=PrefixType.LOOPBACK,
                    **entry_kwargs,
                )
            ]
        )
        return prefix

    def get_fib_routes(self):
        return self.fib.get_route_db()
