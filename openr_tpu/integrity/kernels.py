"""Fused audit kernels for the integrity plane — one dispatch, scalar
readback, per tier and backend.

Tier 1 (fixed-point residual): the resident distance product is the
unique fixed point of its backend's min-plus relaxation, so ONE extra
relax pass must be the identity. The kernels reuse the exact relax
bodies the solvers run (``route_sweep._rev_relax``,
``spf_grouped._grouped_relax``, ``spf_sparse._uniform_relax``) — any
divergence between audit and solve semantics would alarm on healthy
state. Cost O(nnz); readback is one int32 violation count.

Blind spot (documented, covered by tier 2): min-relax only LOWERS, so a
corrupted cell that was RAISED is caught (an uncorrupted neighbor
re-derives the shorter true value), but a cell LOWERED to a value that
enables no shorter neighbor path — or a raised diagonal still below the
shortest cycle — survives one relax pass. The ``device.corrupt_resident``
seam therefore always flips a bit in the packed product too, which
tier 2 catches unconditionally.

Tier 2 (mirror digest): per-row FNV-1a-32 over the raw uint32 words of
the packed product, folded with a WRAPAROUND uint32 SUM over rows. The
row fold is order-independent on purpose: shard order and slot order
then cannot perturb the digest, so device (sharded or not) and host
mirror agree bit-for-bit or the state diverged. Readback is one uint32.

Tier 3 (sampled row oracle): the seeded row subset re-solved COLD from
unit init through the backend's own fixed-point driver and bit-compared
against the resident rows — end-to-end ground truth at O(sample) cost.

This package is intentionally OUTSIDE the sharding-spec lint scope
(``openr_tpu/ops/``, ``openr_tpu/decision/``): audit dispatches are
read-only probes off the churn path; bare ``jit`` under GSPMD keeps
them placement-agnostic across the single-chip and mesh engines.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from openr_tpu.ops import route_sweep as rs
from openr_tpu.ops import spf_grouped as sg
from openr_tpu.ops import spf_sparse
from openr_tpu.ops.spf import INF

__all__ = [
    "fnv_device",
    "fnv_host",
    "fnv_slots",
    "ell_residual",
    "ell_sample_oracle",
    "grouped_residual",
    "grouped_sample_oracle",
    "world_residual",
    "world_cold_slot",
]

_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193


def _fnv_rows(words):
    """Per-row FNV-1a-32 over uint32 words: [R, W] -> [R]."""
    h0 = jnp.full((words.shape[0],), _FNV_OFFSET, dtype=jnp.uint32)

    def step(h, col):
        return (h ^ col) * jnp.uint32(_FNV_PRIME), None

    h, _ = jax.lax.scan(step, h0, jnp.transpose(words))
    return h


@jax.jit
def fnv_device(arr):
    """Order-independent digest of a resident int32 [R, W] array: sum
    (mod 2^32) of per-row FNV-1a digests. One uint32 readback."""
    words = jax.lax.bitcast_convert_type(arr, jnp.uint32)
    return jnp.sum(_fnv_rows(words), dtype=jnp.uint32)


@jax.jit
def fnv_slots(arr3):
    """Per-slot digests of a [slots, R, W] world block: vmapped row
    fold, [slots] uint32 out. The host folds occupied slots only."""
    words = jax.lax.bitcast_convert_type(arr3, jnp.uint32)
    return jax.vmap(
        lambda w2: jnp.sum(_fnv_rows(w2), dtype=jnp.uint32)
    )(words)


def fnv_host(arr: np.ndarray) -> int:
    """NumPy replica of ``fnv_device`` over a host mirror (bit-exact:
    same per-row FNV-1a, same wraparound row sum)."""
    words = np.ascontiguousarray(
        np.asarray(arr, dtype=np.int32)
    ).view(np.uint32)
    h = np.full(words.shape[0], _FNV_OFFSET, dtype=np.uint32)
    prime = np.uint32(_FNV_PRIME)
    for j in range(words.shape[1]):
        h = (h ^ words[:, j]) * prime
    return int(np.sum(h, dtype=np.uint32))


# -- tier 1: residual ---------------------------------------------------


@functools.partial(jax.jit, static_argnames=("bands",))
def ell_residual(dr, v_t, w_t, overloaded, bands):
    """ELL backends: violation count of one extra reversed relax over
    ALL resident destination rows (padding rows included — they were
    solved to fixed points too)."""
    t_ids = jnp.arange(dr.shape[0], dtype=jnp.int32)
    nxt = rs._rev_relax(dr, bands, v_t, w_t, overloaded, t_ids)
    return jnp.sum((nxt != dr).astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("meta", "impl"))
def grouped_residual(dr, v_t, w_t, overloaded, meta, impl):
    """Grouped backend: same identity check through the per-segment
    dense contraction the grouped solver runs."""
    t_ids = jnp.arange(dr.shape[0], dtype=jnp.int32)
    nxt = sg._grouped_relax(
        dr, meta, v_t, w_t, overloaded, t_ids, impl=impl
    )
    return jnp.sum((nxt != dr).astype(jnp.int32))


@jax.jit
def world_residual(src3, w3, ov2, d3):
    """World block: vmapped uniform-ELL relax identity over EVERY slot
    of a bucket. Vacated slots hold their last (stale but coherent)
    fixed point and never-occupied slots are all-zero — both are relax
    fixed points, so auditing the full block needs no occupancy mask."""

    def one(src, w, ov, d):
        nxt = spf_sparse._uniform_relax(d, src, w, ov)
        return jnp.sum((nxt != d).astype(jnp.int32))

    return jnp.sum(jax.vmap(one)(src3, w3, ov2, d3))


# -- tier 3: sampled cold oracle ---------------------------------------


@functools.partial(jax.jit, static_argnames=("bands", "n"))
def ell_sample_oracle(dr, ids, v_t, w_t, overloaded, bands, n):
    """Rows ``ids`` re-solved cold through the ELL fixed-point driver;
    returns how many differ from the resident rows anywhere."""
    cold = rs._rev_fixed_point(bands, v_t, w_t, overloaded, ids, n)
    return jnp.sum(jnp.any(cold != dr[ids], axis=1).astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("meta", "n", "impl"))
def grouped_sample_oracle(dr, ids, v_t, w_t, overloaded, meta, n, impl):
    cold = sg._grouped_fixed_point(
        meta, v_t, w_t, overloaded, ids, n, reverse=True, impl=impl
    )
    return jnp.sum(jnp.any(cold != dr[ids], axis=1).astype(jnp.int32))


@jax.jit
def world_cold_slot(src, w, overloaded, srcs):
    """Cold re-solve of ONE world slot's distance plane, replicating
    ``spf_sparse._tenant_view_solve``'s cold path exactly (unit init,
    unmasked first relax so overloaded sources originate, masked relax
    to the fixed point) — bit-identical by the unique-fixed-point
    argument."""
    s = srcs.shape[0]
    n = src.shape[0]
    unit = jnp.full((s, n), INF, dtype=jnp.int32)
    unit = unit.at[jnp.arange(s), srcs].set(0)
    no_overload = jnp.zeros_like(overloaded)
    d0 = spf_sparse._uniform_relax(unit, src, w, no_overload)

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    def body(state):
        d, _, it = state
        nxt = spf_sparse._uniform_relax(d, src, w, overloaded)
        return nxt, jnp.any(nxt < d), it + 1

    d, _, _ = jax.lax.while_loop(
        cond, body, (d0, jnp.bool_(True), jnp.int32(0))
    )
    return d
