"""Integrity plane: silent-corruption detection, quarantine, and warm
healing for all resident device state.

Import surface is deliberately LIGHT — engines import the contract at
module load and Decision reads ``quarantine_active`` on its gauge path;
the jax-heavy audit kernels load lazily behind ``get_auditor()`` use.
"""

from openr_tpu.integrity.contract import ResidentEngineContract

__all__ = [
    "ResidentEngineContract",
    "get_auditor",
    "reset_auditor",
    "quarantine_active",
]


def get_auditor():
    from openr_tpu.integrity.auditor import get_auditor as _get

    return _get()


def reset_auditor() -> None:
    from openr_tpu.integrity.auditor import reset_auditor as _reset

    _reset()


def quarantine_active() -> bool:
    """True while any engine failed its last audit and has not yet
    re-audited clean. Touches no jax state and instantiates nothing —
    safe on gauge-sample paths."""
    from openr_tpu.integrity import auditor as _auditor

    a = _auditor._AUDITOR
    return a is not None and a.quarantine_active()
