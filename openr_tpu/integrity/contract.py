"""The ONE resident-engine contract (ROADMAP: "One resident-engine
interface").

Every warm compute plane in this repo is the same shape: device-RESIDENT
buffers a fused dispatch re-reads (``@resident_buffers``), a host-side
patch JOURNAL that keeps warm solves sound across churn, a liveness
PROBE, a DELTA READBACK that settles host mirrors on success only,
SNAPSHOT/REHYDRATE warm-start material, and — new in the integrity
plane — a budget-bounded AUDIT surface over the residents. Features
kept landing per-backend (frontier: ELL-only, recovery: ELL-first,
tenancy: its own hooks); this module names the contract once so the
ELL, grouped, sharded and world-batch engines implement it and
capabilities written against it hold everywhere.

Dependency-light ON PURPOSE: no jax, no numpy — the annotated engines
import this at module load, and ``make lint-analysis`` (which never
touches an accelerator runtime) walks the same classes.

The audit surface (all implementations budget-bounded; called from
Decision's post-converge hook, NEVER inside a solve window):

- ``audit_residual`` — tier 1: one extra min-plus relax pass over the
  resident distances must be the identity (the fixed point is unique);
  returns the scalar violation count from one fused dispatch.
- ``audit_digest_pair`` — tier 2: FNV-1a digest of the resident packed
  product on device vs the settle-on-success host mirror's digest
  (scalar readback, no row transfer).
- ``audit_sample_rows`` — tier 3: a seeded row subset re-solved COLD on
  device and bit-compared against the resident rows.

Detection flows quarantine -> heal: ``quarantine`` poisons the warm
rung (the engine's next event walks the degradation ladder to a cold
rebuild), ``integrity_heal`` is the cheaper warm path the auditor
tries first — re-land the residents from uncorrupted material (band
tensors, host mirrors) with no layout recompile, then re-audit.
Either way routes never flap: the healed product is bit-identical, so
Fib sees at most one delta and zero deletes.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Optional, Sequence, Tuple


class ResidentEngineContract(ABC):
    """Abstract resident-engine protocol: residents + journal + probe +
    delta readback + snapshot/rehydrate + audit.

    Implementors: ``ops.route_engine.RouteSweepEngine`` (ELL, and via
    ``mesh=`` the sharded variant), ``GroupedRouteSweepEngine``, and
    ``ops.world_batch.WorldManager`` (the tenant plane audits all its
    bucket blocks as one engine).
    """

    #: short engine-class tag stamped into spans/artifacts
    audit_kind: str = "resident"

    # -- audit plane (tiers 1..3) -------------------------------------

    @abstractmethod
    def audit_ready(self) -> bool:
        """True when the residents are settled and mirrored — no
        pending delta in flight, no host-fallback staleness, no
        unsolved tenant. Audits are skipped (counted) otherwise."""

    @abstractmethod
    def audit_residual(self) -> int:
        """Tier 1: violation count of one extra relax pass (0 == the
        resident distances are a fixed point)."""

    @abstractmethod
    def audit_digest_pair(self) -> Tuple[int, int]:
        """Tier 2: (device digest, host-mirror digest) of the resident
        packed product. Equal unless the device copy silently drifted
        from the settle-on-success mirror."""

    @abstractmethod
    def audit_row_count(self) -> int:
        """Population the tier-3 sampler draws from (rows/lanes)."""

    @abstractmethod
    def audit_sample_rows(self, rows: Sequence[int]) -> int:
        """Tier 3: re-solve the given rows cold on device; return how
        many mismatch the resident rows bit-for-bit."""

    # -- quarantine / heal --------------------------------------------

    @abstractmethod
    def quarantine(self, reason: str) -> None:
        """Poison the warm plane: no later warm dispatch may read the
        (possibly corrupt) residents. The engine's own degradation
        ladder then cold-rebuilds on the next event even if
        ``integrity_heal`` is never called."""

    @abstractmethod
    def integrity_heal(self) -> bool:
        """Warm heal: re-land every resident from uncorrupted material
        (band tensors / host mirrors) WITHOUT a host layout recompile.
        Returns True when the engine believes it is healed; the
        auditor re-audits before counting the heal."""

    # -- fault seam ----------------------------------------------------

    @abstractmethod
    def corrupt_resident(self, seed: int) -> None:
        """Deterministic ``device.corrupt_resident`` seam: flip seeded
        bits in the live residents so tests and chaos storms can prove
        detection-within-one-cadence and bit-identical healing."""

    # -- snapshot / rehydrate (state plane) ---------------------------

    def snapshot_resident_state(self) -> Optional[Any]:
        """Warm-start material sufficient to re-land the residents
        bit-identically (versions + host copies). None when the engine
        has nothing sound to snapshot (mid-fallback, unsolved)."""
        return None

    def rehydrate_resident_state(self, snap: Any) -> bool:
        """Re-land residents from ``snapshot_resident_state`` output.
        Version/identity-gated: a stale or foreign snapshot returns
        False and the engine stays on its cold path (never wrong)."""
        return False
