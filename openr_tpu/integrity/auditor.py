"""The integrity auditor: cadence-gated audits over every registered
resident engine, with quarantine-and-heal orchestration.

Engines self-register on construction (a ``weakref.WeakSet`` — the
auditor never keeps an engine alive). Decision's post-converge hook
calls ``on_converge()``; tiers 1+2 run per audited call (one fused
dispatch + one uint32 readback each), tier 3 every ``oracle_every``-th
call, the whole hook rate-limited to one audit pass per
``min_interval_s`` of wall clock so converge storms stay cheap.
Audits ride idle post-converge windows ONLY — never inside a solve
window (the residual dispatch would interleave with an in-flight delta
readback and alarm on healthy state).

Detection path per engine: bump ``integrity.violations.<tier>`` +
``integrity.quarantines``, poison the warm rung via
``engine.quarantine()`` (so the degradation ladder cold-rebuilds even
if nothing else happens), then try the cheap warm heal
(``engine.integrity_heal()``) and RE-AUDIT with the oracle forced. The
heal deliberately does NOT refresh the host mirror first: the re-audit
digest compares the healed device product against the PRE-corruption
settle-on-success mirror, so a heal that fails to reproduce the exact
bits counts as ``integrity.heal_failures`` and the engine stays
quarantined for the ladder's cold rebuild. Either way routes never
flap — the healed product is bit-identical, Fib sees zero deletes.
"""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from openr_tpu.integrity.contract import ResidentEngineContract
from openr_tpu.telemetry import get_flight_recorder, get_registry, get_tracer


class IntegrityAuditor:
    """Process-global audit scheduler over the registered engines."""

    def __init__(self, oracle_every: int = 8, sample_rows: int = 4,
                 seed: int = 0, min_interval_s: float = 1.0) -> None:
        assert oracle_every >= 1 and sample_rows >= 1
        self.oracle_every = oracle_every
        self.sample_rows = sample_rows
        self.min_interval_s = min_interval_s
        self._seed = seed
        self._last_audit_t: Optional[float] = None
        self._lock = threading.Lock()
        self._engines: "weakref.WeakSet[ResidentEngineContract]" = (
            weakref.WeakSet()
        )
        self._quarantined: "weakref.WeakSet[ResidentEngineContract]" = (
            weakref.WeakSet()
        )
        self._converges = 0

    # -- registry ------------------------------------------------------

    def register(self, engine: ResidentEngineContract) -> None:
        with self._lock:
            self._engines.add(engine)

    def unregister(self, engine: ResidentEngineContract) -> None:
        with self._lock:
            self._engines.discard(engine)
            self._quarantined.discard(engine)

    def quarantine_active(self) -> bool:
        """True while any registered engine failed its last audit and
        has not yet re-audited clean (drives
        ``decision.route_staleness_ms``)."""
        return len(self._quarantined) > 0

    # -- cadence -------------------------------------------------------

    def on_converge(self) -> None:
        """Post-converge hook: audit every registered engine. Tiers
        1+2 each call; tier 3 every ``oracle_every``-th. Cheap early
        out when nothing is registered, and WALL-CLOCK rate-limited
        (``min_interval_s``) so a converge storm — thousands of
        debounce fires per second under sustained load — pays at most
        a few audit dispatches per second, not one per converge.
        Audit errors are contained — a broken audit must never take
        down the Decision loop."""
        with self._lock:
            engines = list(self._engines)
        if not engines:
            return
        now = time.monotonic()
        if (
            self._last_audit_t is not None
            and now - self._last_audit_t < self.min_interval_s
        ):
            return
        self._last_audit_t = now
        self._converges += 1
        force_oracle = (self._converges % self.oracle_every) == 0
        for engine in engines:
            try:
                self.audit_engine(engine, force_oracle=force_oracle)
            except Exception:
                get_registry().counter_bump("integrity.audit_errors")

    def audit_now(self) -> List[Dict[str, Any]]:
        """Forced full audit (oracle included) of every engine —
        tools/tests surface; raises nothing, reports per engine."""
        with self._lock:
            engines = list(self._engines)
        self._converges += 1
        out = []
        for engine in engines:
            try:
                out.append(self.audit_engine(engine, force_oracle=True))
            except Exception as exc:
                get_registry().counter_bump("integrity.audit_errors")
                out.append({
                    "kind": getattr(engine, "audit_kind", "?"),
                    "verdict": "error", "error": repr(exc),
                })
        return out

    # -- one engine ----------------------------------------------------

    def audit_engine(self, engine: ResidentEngineContract,
                     force_oracle: bool = False) -> Dict[str, Any]:
        reg = get_registry()
        if not engine.audit_ready():
            reg.counter_bump("integrity.skipped")
            return {"kind": engine.audit_kind, "verdict": "skipped"}
        tracer = get_tracer()
        span = tracer.span_active("integrity.audit")
        reg.counter_bump("integrity.audits")
        tier = ""
        verdict = "error"
        try:
            tier = self._detect(engine, force_oracle) or ""
            if not tier:
                self._quarantined.discard(engine)
                verdict = "clean"
            else:
                reg.counter_bump(f"integrity.violations.{tier}")
                reg.counter_bump("integrity.quarantines")
                self._quarantined.add(engine)
                engine.quarantine(f"integrity audit: {tier} violation")
                get_flight_recorder().anomaly(
                    "quarantine",
                    reason=f"{engine.audit_kind}: {tier} violation",
                    audit_kind=engine.audit_kind,
                    tier=tier,
                )
                healed = False
                try:
                    healed = bool(engine.integrity_heal())
                except Exception:
                    reg.counter_bump("integrity.heal_errors")
                if (
                    healed
                    and engine.audit_ready()
                    and self._detect(engine, force_oracle=True) is None
                ):
                    reg.counter_bump("integrity.heals")
                    self._quarantined.discard(engine)
                    verdict = "healed"
                else:
                    reg.counter_bump("integrity.heal_failures")
                    verdict = "quarantined"
        finally:
            tracer.end_span_active(
                span, kind=engine.audit_kind, verdict=verdict, tier=tier
            )
            get_flight_recorder().note(
                "audit", audit_kind=engine.audit_kind, verdict=verdict,
                tier=tier,
            )
        return {
            "kind": engine.audit_kind, "verdict": verdict, "tier": tier,
        }

    def _detect(self, engine: ResidentEngineContract,
                force_oracle: bool) -> Optional[str]:
        """Run the tiers cheapest-first; return the first violated
        tier's name, or None when the residents audit clean."""
        if int(engine.audit_residual()):
            return "residual"
        dev, host = engine.audit_digest_pair()
        if int(dev) != int(host):
            return "digest"
        if force_oracle:
            count = int(engine.audit_row_count())
            if count > 0:
                rng = random.Random(
                    self._seed * 1_000_003 + self._converges
                )
                k = min(self.sample_rows, count)
                rows = sorted(rng.sample(range(count), k))
                if int(engine.audit_sample_rows(rows)):
                    return "oracle"
        return None


_AUDITOR: Optional[IntegrityAuditor] = None
_GLOBAL_LOCK = threading.Lock()


def get_auditor() -> IntegrityAuditor:
    global _AUDITOR
    if _AUDITOR is None:
        with _GLOBAL_LOCK:
            if _AUDITOR is None:
                _AUDITOR = IntegrityAuditor()
    return _AUDITOR


def reset_auditor() -> None:
    """Test/tool isolation: drop the global auditor (engines
    re-register on construction; existing engines are forgotten)."""
    global _AUDITOR
    with _GLOBAL_LOCK:
        _AUDITOR = None
