"""PersistentStore: disk-backed store for state that must survive restart.

Behavioral parity with the reference ``openr/config-store/PersistentStore``
(PersistentStore.h:55): async batched writes with atomic on-disk commit
(tmp + rename + fsync), typed object load/store over the wire codec.
Used for drain/overload state, allocated prefixes and node labels
(reference: Main.cpp:479-480, PrefixAllocator).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, Optional

from openr_tpu.telemetry import get_registry
from openr_tpu.utils import wire
from openr_tpu.utils.eventbase import AsyncThrottle, OpenrEventBase

log = logging.getLogger(__name__)


class PersistentStore:
    def __init__(self, path: str, save_throttle_s: float = 0.1):
        self._path = path
        self._lock = threading.Lock()
        self._data: Dict[str, bytes] = {}
        self.num_writes = 0
        self.num_saves = 0
        self._load_from_disk()
        self.evb = OpenrEventBase(name=f"config-store")
        self._save_throttled = AsyncThrottle(
            self.evb, save_throttle_s, self._save_to_disk
        )
        self.evb.run_in_thread()

    # -- lifecycle --------------------------------------------------------

    def stop(self) -> None:
        # flush pending writes synchronously before shutdown
        self.evb.call_and_wait(self._save_to_disk)
        self.evb.stop()
        self.evb.join()

    # -- public API -------------------------------------------------------

    def store(self, key: str, obj: Any) -> None:
        """Store any wire-encodable object (dataclass, dict, list, ...)."""
        payload = wire.dumps(obj)
        with self._lock:
            self._data[key] = payload
            self.num_writes += 1
        self._save_throttled()

    def load(self, key: str, cls: Any = None) -> Optional[Any]:
        with self._lock:
            payload = self._data.get(key)
        if payload is None:
            return None
        return wire.loads(payload, cls if cls is not None else Any)

    def erase(self, key: str) -> bool:
        with self._lock:
            existed = key in self._data
            self._data.pop(key, None)
        if existed:
            self._save_throttled()
        return existed

    def keys(self):
        with self._lock:
            return sorted(self._data)

    # -- disk I/O ---------------------------------------------------------

    def _load_from_disk(self) -> None:
        try:
            with open(self._path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            self._data = {}
            return
        try:
            self._data = dict(wire.loads(raw, Dict[str, bytes]))
        except (ValueError, TypeError, IndexError, EOFError) as exc:
            # Corrupt/truncated store: start empty, but never silently.
            # The bad bytes are parked at the .tmp sibling for forensics
            # (the next atomic save overwrites .tmp last, so the evidence
            # survives until a healthy save lands).
            self._data = {}
            get_registry().counter_bump("config_store.load_errors")
            tmp = f"{self._path}.tmp"
            try:
                if not os.path.exists(tmp):
                    with open(tmp, "wb") as f:
                        f.write(raw)
            except OSError:
                pass
            log.error(
                "config-store %s unreadable (%d bytes): %s; starting "
                "empty, corrupt bytes kept at %s",
                self._path, len(raw), exc, tmp,
            )

    def _save_to_disk(self) -> None:
        with self._lock:
            raw = wire.dumps(dict(self._data))
            self.num_saves += 1
        tmp = f"{self._path}.tmp"
        os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(raw)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)
