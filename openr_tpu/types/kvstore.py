"""KvStore wire types: versioned values, publications, sync params.

Schema parity with the reference IDL ``openr/if/KvStore.thrift``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

# TTL value indicating a key never expires.
# reference: openr/common/Constants.h kTtlInfinity
TTL_INFINITY = -(2 ** 31)

DEFAULT_AREA = "0"


@dataclass
class Value:
    """A versioned, TTL'd value. The CRDT unit of the flooded LSDB.

    Merge ordering: (version, originatorId, value) — see
    ``openr_tpu.kvstore.store.merge_key_values``.
    reference: openr/if/KvStore.thrift:21-41
    """

    version: int = 0
    originator_id: str = ""
    value: Optional[bytes] = None
    ttl: int = TTL_INFINITY
    ttl_version: int = 0
    hash: Optional[int] = None


@dataclass
class Publication:
    """A batch of updated key/values flooded between stores.

    reference: openr/if/KvStore.thrift:229+
    """

    key_vals: Dict[str, Value] = field(default_factory=dict)
    expired_keys: List[str] = field(default_factory=list)
    nodes: Optional[List[str]] = None
    tobe_updated_keys: Optional[List[str]] = None
    flood_root_id: Optional[str] = None
    area: str = DEFAULT_AREA
    # in-process only (never serialized): the telemetry trace born at
    # set_key_vals, carried to Decision for span accumulation
    trace: Optional[object] = None


@dataclass
class KeySetParams:
    """reference: openr/if/KvStore.thrift:62+"""

    key_vals: Dict[str, Value] = field(default_factory=dict)
    solicit_response: bool = True
    originator_id: str = ""
    flood_root_id: Optional[str] = None
    timestamp_ms: Optional[int] = None


@dataclass
class KeyGetParams:
    keys: List[str] = field(default_factory=list)


@dataclass
class KeyDumpParams:
    """reference: openr/if/KvStore.thrift:91+"""

    prefix: str = ""
    originator_ids: Set[str] = field(default_factory=set)
    keys: Optional[List[str]] = None
    # if set, only respond with values whose (version, originator, value)
    # hash differs from the one supplied here (anti-entropy sync)
    key_val_hashes: Optional[Dict[str, Value]] = None


class KvStorePeerState(enum.IntEnum):
    """Per-peer sync FSM. reference: openr/kvstore/KvStore.h:46-50"""

    IDLE = 0
    SYNCING = 1
    INITIALIZED = 2


@dataclass
class PeerSpec:
    """How to reach a peer store. reference: openr/if/KvStore.thrift:119+"""

    peer_addr: str = ""
    ctrl_port: int = 0
    state: KvStorePeerState = KvStorePeerState.IDLE
