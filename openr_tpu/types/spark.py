"""Spark neighbor-discovery wire messages and events.

Schema parity with the reference IDL ``openr/if/Spark.thrift`` (hello /
handshake / heartbeat packets, SparkNeighborEvent) — field semantics kept,
layout re-expressed as dataclasses over the canonical wire codec.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from openr_tpu.types.network import BinaryAddress, IpPrefix


@dataclass(frozen=True)
class ReflectedNeighborInfo:
    """What I know about having heard you (echoed in my hellos so you can
    confirm bidirectional visibility and compute RTT).
    reference: Spark.thrift ReflectedNeighborInfo."""

    seq_num: int = 0
    last_nbr_msg_sent_ts_us: int = 0  # your hello's sentTs as I saw it
    last_my_msg_rcvd_ts_us: int = 0  # when I received it (my clock)


@dataclass
class SparkHelloMsg:
    """reference: Spark.thrift SparkHelloMsg."""

    node_name: str
    if_name: str
    seq_num: int
    neighbor_infos: Dict[str, ReflectedNeighborInfo] = field(
        default_factory=dict
    )
    version: int = 1
    solicit_response: bool = False
    restarting: bool = False
    sent_ts_us: int = 0


@dataclass
class SparkHandshakeMsg:
    """reference: Spark.thrift SparkHandshakeMsg."""

    node_name: str
    if_name: str
    is_adj_established: bool = False
    hold_time_ms: int = 3000
    graceful_restart_time_ms: int = 30000
    transport_address_v6: BinaryAddress = field(default_factory=BinaryAddress)
    transport_address_v4: BinaryAddress = field(default_factory=BinaryAddress)
    openr_ctrl_port: int = 2018
    area: str = "0"
    # receiver targeting: when set, only this neighbor should process
    neighbor_node_name: Optional[str] = None
    # the sender's KvStore peer-sync port (reference: Spark.thrift:97
    # kvStoreCmdPort); 0 when cross-process peering is not exposed.
    # TRAILING deliberately: the wire codec decodes positionally and
    # only forward-compats unknown trailing fields, so a mixed-version
    # neighborhood (old daemon, new handshake) still negotiates
    kvstore_peer_port: int = 0


@dataclass
class SparkHeartbeatMsg:
    """reference: Spark.thrift SparkHeartbeatMsg."""

    node_name: str
    if_name: str
    seq_num: int = 0
    hold_time_ms: int = 3000


@dataclass
class SparkPacket:
    """Envelope: exactly one of the messages is set."""

    hello: Optional[SparkHelloMsg] = None
    handshake: Optional[SparkHandshakeMsg] = None
    heartbeat: Optional[SparkHeartbeatMsg] = None
    version: int = 1


class SparkNeighborEventType(enum.IntEnum):
    """reference: Spark.thrift SparkNeighborEventType."""

    NEIGHBOR_UP = 1
    NEIGHBOR_DOWN = 2
    NEIGHBOR_RESTARTING = 3
    NEIGHBOR_RESTARTED = 4
    NEIGHBOR_RTT_CHANGE = 5


@dataclass
class SparkNeighbor:
    """Info about an established neighbor carried in events."""

    node_name: str
    local_if_name: str
    remote_if_name: str
    transport_address_v6: BinaryAddress = field(default_factory=BinaryAddress)
    transport_address_v4: BinaryAddress = field(default_factory=BinaryAddress)
    openr_ctrl_port: int = 2018
    area: str = "0"
    rtt_us: int = 0
    # reference: Spark.thrift:97 kvStoreCmdPort (trailing: see
    # SparkHandshakeMsg)
    kvstore_peer_port: int = 0


@dataclass
class SparkNeighborEvent:
    event_type: SparkNeighborEventType
    neighbor: SparkNeighbor


@dataclass(frozen=True)
class InterfaceInfo:
    """reference: openr/if/Lsdb.thrift InterfaceInfo."""

    is_up: bool
    if_index: int = 0
    networks: Tuple[IpPrefix, ...] = ()

    def __post_init__(self) -> None:
        if not isinstance(self.networks, tuple):
            object.__setattr__(self, "networks", tuple(self.networks))


@dataclass
class InterfaceDatabase:
    """reference: openr/if/Lsdb.thrift InterfaceDatabase."""

    this_node_name: str = ""
    interfaces: Dict[str, InterfaceInfo] = field(default_factory=dict)
