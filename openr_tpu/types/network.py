"""Network-layer value types.

Schema parity with the reference IDL ``openr/if/Network.thrift`` (BinaryAddress,
IpPrefix, MplsAction, NextHopThrift, UnicastRoute, MplsRoute), re-expressed as
immutable Python dataclasses with canonical ordering/hashing so they can be
used in sets and sorted deterministically (the reference relies on
unordered_set + thrift comparators).
"""

from __future__ import annotations

import enum
import ipaddress
from dataclasses import dataclass, field
from typing import Optional, Tuple


class MplsActionCode(enum.IntEnum):
    # reference: openr/if/Network.thrift:27-33
    PUSH = 0
    SWAP = 1
    PHP = 2  # pen-ultimate hop popping: POP and FORWARD
    POP_AND_LOOKUP = 3
    NOOP = 4


class PrefixType(enum.IntEnum):
    # reference: openr/if/Network.thrift:104-119
    LOOPBACK = 1
    DEFAULT = 2
    BGP = 3
    PREFIX_ALLOCATOR = 4
    BREEZE = 5
    RIB = 6
    SLO_PREFIX_ALLOCATOR = 7
    TYPE_1 = 21
    TYPE_2 = 22
    TYPE_3 = 23
    TYPE_4 = 24
    TYPE_5 = 25


class AdminDistance(enum.IntEnum):
    # reference: openr/if/Network.thrift:18-25
    DIRECTLY_CONNECTED = 0
    STATIC_ROUTE = 1
    EBGP = 20
    IBGP = 200
    NETLINK_LISTENER = 225
    MAX_ADMIN_DISTANCE = 255


@dataclass(frozen=True, order=True)
class BinaryAddress:
    """An IP address as raw bytes, optionally scoped to an interface.

    reference: openr/if/Network.thrift:55-58
    """

    addr: bytes = b""
    if_name: Optional[str] = None

    @staticmethod
    def from_str(s: str, if_name: Optional[str] = None) -> "BinaryAddress":
        return BinaryAddress(addr=ipaddress.ip_address(s).packed, if_name=if_name)

    @property
    def is_v4(self) -> bool:
        return len(self.addr) == 4

    def to_str(self) -> str:
        if not self.addr:
            return ""
        return str(ipaddress.ip_address(self.addr))

    def __repr__(self) -> str:  # compact, operator friendly
        scope = f"%{self.if_name}" if self.if_name else ""
        return f"Addr({self.to_str()}{scope})"


@dataclass(frozen=True, order=True)
class IpPrefix:
    """reference: openr/if/Network.thrift:60-63"""

    prefix_address: BinaryAddress = field(default_factory=BinaryAddress)
    prefix_length: int = 0

    @staticmethod
    def from_str(s: str) -> "IpPrefix":
        net = ipaddress.ip_network(s, strict=False)
        return IpPrefix(
            prefix_address=BinaryAddress(addr=net.network_address.packed),
            prefix_length=net.prefixlen,
        )

    @property
    def is_v4(self) -> bool:
        return self.prefix_address.is_v4

    def to_str(self) -> str:
        return f"{self.prefix_address.to_str()}/{self.prefix_length}"

    def __repr__(self) -> str:
        return f"Prefix({self.to_str()})"


@dataclass(frozen=True)
class MplsAction:
    """reference: openr/if/Network.thrift:46-52

    ``push_labels``: index 0 is bottom-of-stack, last is top-of-stack.
    """

    action: MplsActionCode = MplsActionCode.NOOP
    swap_label: Optional[int] = None
    push_labels: Optional[Tuple[int, ...]] = None

    def __post_init__(self) -> None:
        if self.push_labels is not None and not isinstance(self.push_labels, tuple):
            object.__setattr__(self, "push_labels", tuple(self.push_labels))

    def _key(self):
        return (int(self.action), self.swap_label or 0, self.push_labels or ())

    def __lt__(self, other: "MplsAction") -> bool:
        return self._key() < other._key()


@dataclass(frozen=True)
class NextHop:
    """A resolved next-hop: address + egress interface + cost (+MPLS action).

    reference: NextHopThrift, openr/if/Network.thrift:65-95
    """

    address: BinaryAddress = field(default_factory=BinaryAddress)
    weight: int = 0  # 0 == ECMP member
    mpls_action: Optional[MplsAction] = None
    metric: int = 0
    area: Optional[str] = None
    neighbor_node_name: Optional[str] = None

    def _key(self):
        return (
            self.address,
            self.weight,
            self.mpls_action._key() if self.mpls_action else (),
            self.metric,
            self.area or "",
            self.neighbor_node_name or "",
        )

    def __lt__(self, other: "NextHop") -> bool:
        return self._key() < other._key()


@dataclass(frozen=True)
class UnicastRoute:
    """reference: openr/if/Network.thrift:121-135"""

    dest: IpPrefix
    next_hops: Tuple[NextHop, ...] = ()
    admin_distance: Optional[AdminDistance] = None
    prefix_type: Optional[PrefixType] = None
    data: Optional[bytes] = None
    do_not_install: bool = False

    def __post_init__(self) -> None:
        # canonical next-hop ordering => byte-identical serialized routes
        object.__setattr__(
            self, "next_hops", tuple(sorted(self.next_hops, key=lambda n: n._key()))
        )


@dataclass(frozen=True)
class MplsRoute:
    """reference: openr/if/Network.thrift:97-101"""

    top_label: int
    next_hops: Tuple[NextHop, ...] = ()
    admin_distance: Optional[AdminDistance] = None

    def __post_init__(self) -> None:
        object.__setattr__(
            self, "next_hops", tuple(sorted(self.next_hops, key=lambda n: n._key()))
        )
