"""Link-state database value types.

Schema parity with the reference IDL ``openr/if/Lsdb.thrift``: Adjacency,
AdjacencyDatabase, PrefixMetrics, PrefixEntry, PrefixDatabase, PerfEvents.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from openr_tpu.types.network import BinaryAddress, IpPrefix, PrefixType


class PrefixForwardingType(enum.IntEnum):
    # reference: openr/if/OpenrConfig.thrift PrefixForwardingType
    IP = 0
    SR_MPLS = 1


class PrefixForwardingAlgorithm(enum.IntEnum):
    # reference: openr/if/OpenrConfig.thrift PrefixForwardingAlgorithm
    SP_ECMP = 0
    KSP2_ED_ECMP = 1


class CompareType(enum.IntEnum):
    """How a metric entity present in only one vector compares.
    reference: openr/if/Lsdb.thrift:165-173 CompareType."""

    WIN_IF_PRESENT = 1
    WIN_IF_NOT_PRESENT = 2
    IGNORE_IF_NOT_PRESENT = 3


@dataclass(frozen=True)
class MetricEntity:
    """reference: openr/if/Lsdb.thrift:175-195 MetricEntity."""

    type: int
    priority: int
    op: CompareType = CompareType.WIN_IF_PRESENT
    is_best_path_tie_breaker: bool = False
    metric: Tuple[int, ...] = ()

    def __post_init__(self) -> None:
        if not isinstance(self.metric, tuple):
            object.__setattr__(self, "metric", tuple(self.metric))


@dataclass(frozen=True)
class MetricVector:
    """reference: openr/if/Lsdb.thrift:197-206 MetricVector."""

    version: int = 1
    metrics: Tuple[MetricEntity, ...] = ()

    def __post_init__(self) -> None:
        if not isinstance(self.metrics, tuple):
            object.__setattr__(self, "metrics", tuple(self.metrics))

    def sorted_metrics(self):
        return sorted(self.metrics, key=lambda e: -e.priority)


@dataclass(frozen=True)
class PerfEvent:
    """reference: openr/if/Lsdb.thrift:24-28"""

    node_name: str
    event_descr: str
    unix_ts: int = 0


@dataclass
class PerfEvents:
    """reference: openr/if/Lsdb.thrift:30-32"""

    events: List[PerfEvent] = field(default_factory=list)

    def add(self, node_name: str, descr: str) -> None:
        self.events.append(
            PerfEvent(node_name=node_name, event_descr=descr,
                      unix_ts=int(time.time() * 1000))
        )


@dataclass(frozen=True)
class Adjacency:
    """One directed adjacency advertised by a node toward a neighbor.

    reference: openr/if/Lsdb.thrift:69-102
    """

    other_node_name: str
    if_name: str
    metric: int = 1
    next_hop_v6: BinaryAddress = field(default_factory=BinaryAddress)
    next_hop_v4: BinaryAddress = field(default_factory=BinaryAddress)
    adj_label: int = 0
    is_overloaded: bool = False
    rtt: int = 0
    timestamp: int = 0
    weight: int = 1
    other_if_name: str = ""


@dataclass(frozen=True)
class AdjacencyDatabase:
    """Full link-state of a single router, flooded under ``adj:<node>`` keys.

    reference: openr/if/Lsdb.thrift:104-125
    """

    this_node_name: str
    is_overloaded: bool = False
    adjacencies: Tuple[Adjacency, ...] = ()
    node_label: int = 0
    area: str = "0"
    perf_events: Optional[PerfEvents] = None

    def __post_init__(self) -> None:
        if not isinstance(self.adjacencies, tuple):
            object.__setattr__(self, "adjacencies", tuple(self.adjacencies))


@dataclass(frozen=True, order=True)
class PrefixMetrics:
    """Best-route selection metrics. Field order here IS the comparison
    order used by best-route selection: (path_preference DESC,
    source_preference DESC, distance ASC).

    reference: openr/if/Lsdb.thrift PrefixMetrics; comparison semantics
    reference: openr/common/Util.h:549 (selectBestPrefixMetrics tuple)
    """

    version: int = 1
    path_preference: int = 0  # prefer higher
    source_preference: int = 0  # prefer higher
    distance: int = 0  # prefer lower

    def comparison_key(self) -> Tuple[int, int, int]:
        return (self.path_preference, self.source_preference, -self.distance)


@dataclass(frozen=True)
class PrefixEntry:
    """One prefix advertisement from one node.

    reference: openr/if/Lsdb.thrift:263-336
    """

    prefix: IpPrefix
    type: PrefixType = PrefixType.DEFAULT
    forwarding_type: PrefixForwardingType = PrefixForwardingType.IP
    forwarding_algorithm: PrefixForwardingAlgorithm = (
        PrefixForwardingAlgorithm.SP_ECMP
    )
    min_nexthop: Optional[int] = None
    prepend_label: Optional[int] = None
    mv: Optional[MetricVector] = None  # deprecated BGP metric vector
    metrics: PrefixMetrics = field(default_factory=PrefixMetrics)
    tags: Tuple[str, ...] = ()
    area_stack: Tuple[str, ...] = ()
    data: Optional[bytes] = None

    def __post_init__(self) -> None:
        if not isinstance(self.tags, tuple):
            object.__setattr__(self, "tags", tuple(sorted(self.tags)))
        if not isinstance(self.area_stack, tuple):
            object.__setattr__(self, "area_stack", tuple(self.area_stack))


@dataclass(frozen=True)
class PrefixDatabase:
    """All prefixes bound to a router, flooded under ``prefix:`` keys.

    reference: openr/if/Lsdb.thrift:338-354
    """

    this_node_name: str
    prefix_entries: Tuple[PrefixEntry, ...] = ()
    delete_prefix: bool = False
    area: str = "0"
    perf_events: Optional[PerfEvents] = None

    def __post_init__(self) -> None:
        if not isinstance(self.prefix_entries, tuple):
            object.__setattr__(self, "prefix_entries", tuple(self.prefix_entries))
