"""Route database types exchanged between Decision and Fib.

Schema parity with the reference IDL ``openr/if/Fib.thrift``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from openr_tpu.types.lsdb import PerfEvents
from openr_tpu.types.network import IpPrefix, MplsRoute, UnicastRoute


@dataclass
class RouteDatabase:
    """reference: openr/if/Fib.thrift RouteDatabase"""

    this_node_name: str = ""
    unicast_routes: List[UnicastRoute] = field(default_factory=list)
    mpls_routes: List[MplsRoute] = field(default_factory=list)
    perf_events: Optional[PerfEvents] = None

    def canonicalize(self) -> "RouteDatabase":
        """Sort routes so two equal RouteDatabases compare equal."""
        self.unicast_routes.sort(key=lambda r: r.dest)
        self.mpls_routes.sort(key=lambda r: r.top_label)
        return self


@dataclass
class RouteDatabaseDelta:
    """reference: openr/if/Fib.thrift RouteDatabaseDelta"""

    this_node_name: str = ""
    unicast_routes_to_update: List[UnicastRoute] = field(default_factory=list)
    unicast_routes_to_delete: List[IpPrefix] = field(default_factory=list)
    mpls_routes_to_update: List[MplsRoute] = field(default_factory=list)
    mpls_routes_to_delete: List[int] = field(default_factory=list)
    perf_events: Optional[PerfEvents] = None

    def empty(self) -> bool:
        return not (
            self.unicast_routes_to_update
            or self.unicast_routes_to_delete
            or self.mpls_routes_to_update
            or self.mpls_routes_to_delete
        )
