"""Runtime shared-state sanitizer (race-dep), the dynamic companion to
the static ``shared-state`` rule — exactly as :mod:`lockdep` is to the
static ``lock-order`` rule.

The static rule over-approximates (it merges instances by class and
cannot see hand-offs through queues); annotations silence what it gets
wrong. This shim audits the annotations from the other side: it records
every instrumented access as an ``(attr, thread, role, locks-held)``
tuple and flags the FIRST unlocked cross-thread write overlap — two
threads touching the same attribute, at least one writing, with no lock
class in common — without the race having to strike. A
``@thread_confined`` claim that is a lie convicts here the first time
two threads actually touch the attribute.

Usage (tests; production code never imports this on the hot path)::

    dep = LockDepTracker()
    race = RaceTracker(lockdep=dep)
    s = SharedState("SolverService", tracker=race)
    mu = TrackedLock("SolverService._cv", tracker=dep)

    set_thread_role("solver-wave-loop")   # at thread entry
    with mu:
        s.waves = 1                       # locked write: fine
    s.waves                               # unlocked read from another
                                          # role -> RaceViolation

Violations carry the same role vocabulary the static report and
``python -m openr_tpu.analysis --roles`` use (via
:func:`lockdep.set_thread_role`), so a runtime conviction reads like a
static finding: "written under role solver-wave-loop and read under
role ctrl with no common lock class". Locks held are observed through
the paired :class:`lockdep.LockDepTracker`'s per-thread stack, so the
two sanitizers share one notion of "held" and one lock-class identity
(``ClassName._attr``).

Detection is first-overlap, lockdep-style: witnesses accumulate per
attribute and each new access is checked against remembered accesses
from other threads; one violation is recorded per attribute (the first
convicting pair), then the attribute goes quiet. The tracker never
blocks or perturbs scheduling — recording is a dict update under a
short internal mutex.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from openr_tpu.analysis.lockdep import (
    LockDepTracker,
    current_role,
    get_tracker,
)

#: cap on remembered witnesses per attribute — enough for any test
#: harness while bounding memory if an access loop runs hot
_MAX_WITNESSES = 64


@dataclass(frozen=True)
class _AccessWitness:
    """One remembered access to one shared attribute."""

    attr: str
    thread: str
    thread_id: int
    role: str
    write: bool
    locks: Tuple[str, ...]

    def _describe(self) -> str:
        kind = "written" if self.write else "read"
        held = (
            "holding {" + ", ".join(self.locks) + "}"
            if self.locks else "holding no lock"
        )
        return f"{kind} under role {self.role} ({held})"


@dataclass
class RaceViolation:
    """An unlocked cross-thread write overlap on one attribute."""

    attr: str
    first: _AccessWitness
    second: _AccessWitness

    @property
    def roles(self) -> Tuple[str, str]:
        return (self.first.role, self.second.role)

    def __str__(self) -> str:
        return (
            f"shared-state race on {self.attr}: "
            f"{self.first._describe()} and {self.second._describe()} "
            "with no common lock class"
        )


class RaceError(RuntimeError):
    """Raised on overlap when the tracker is in raising mode."""


class RaceTracker:
    """Learns per-attribute access witnesses and convicts the first
    unlocked cross-thread write overlap."""

    def __init__(
        self,
        raise_on_violation: bool = False,
        lockdep: Optional[LockDepTracker] = None,
    ) -> None:
        self._mu = threading.Lock()
        self._lockdep = lockdep if lockdep is not None else get_tracker()
        self._witnesses: Dict[str, List[_AccessWitness]] = {}
        self._convicted: Dict[str, RaceViolation] = {}
        self.raise_on_violation = raise_on_violation
        self.violations: List[RaceViolation] = []

    # -- recording ----------------------------------------------------

    def record(self, attr: str, write: bool) -> None:
        """Record one access to ``attr`` (``"Class.attr"`` identity) by
        the calling thread, stamping its role and the lock classes it
        holds right now."""
        t = threading.current_thread()
        witness = _AccessWitness(
            attr=attr,
            thread=t.name,
            thread_id=threading.get_ident(),
            role=current_role(),
            write=write,
            locks=self._lockdep.held(),
        )
        violation: Optional[RaceViolation] = None
        with self._mu:
            if attr not in self._convicted:
                held = set(witness.locks)
                for prior in self._witnesses.get(attr, ()):
                    if prior.thread_id == witness.thread_id:
                        continue
                    if not (prior.write or witness.write):
                        continue  # read/read never races
                    if held & set(prior.locks):
                        continue  # a common lock class serializes them
                    violation = RaceViolation(attr, prior, witness)
                    self._convicted[attr] = violation
                    self.violations.append(violation)
                    break
            bucket = self._witnesses.setdefault(attr, [])
            if len(bucket) < _MAX_WITNESSES and witness not in bucket:
                bucket.append(witness)
        if violation is not None and self.raise_on_violation:
            raise RaceError(str(violation))

    def reset(self) -> None:
        with self._mu:
            self._witnesses.clear()
            self._convicted.clear()
            self.violations.clear()


class SharedState:
    """An instrumented attribute bag — the :class:`TrackedLock` analog
    for shared state. Every attribute read/write on an instance records
    into the tracker under ``"ClassName.attr"`` identity, so a test can
    swap one in for a real object's state and let two genuinely
    scheduled threads convict (or clear) an annotation claim.

    Container mutations count as what they are at the attribute level:
    read the attribute out (a recorded read), mutate the container —
    to model the static rule's mutator-call writes, use
    :meth:`mutate`, which records a write and returns the container.
    """

    def __init__(self, class_name: str,
                 tracker: Optional[RaceTracker] = None) -> None:
        object.__setattr__(self, "_cls", class_name)
        object.__setattr__(
            self, "_tracker",
            tracker if tracker is not None else get_race_tracker(),
        )
        object.__setattr__(self, "_values", {})

    def __setattr__(self, name: str, value: object) -> None:
        self._tracker.record(f"{self._cls}.{name}", write=True)
        self._values[name] = value

    def __getattr__(self, name: str) -> object:
        if name.startswith("_"):
            raise AttributeError(name)
        values = object.__getattribute__(self, "_values")
        if name not in values:
            raise AttributeError(name)
        self._tracker.record(f"{self._cls}.{name}", write=False)
        return values[name]

    def mutate(self, name: str) -> object:
        """Fetch ``name`` for in-place mutation — records a WRITE, the
        runtime twin of the static rule's ``.append``/``.add``/...
        mutator-call accounting."""
        self._tracker.record(f"{self._cls}.{name}", write=True)
        return object.__getattribute__(self, "_values")[name]


_global_tracker: Optional[RaceTracker] = None
_global_mu = threading.Lock()


def get_race_tracker() -> RaceTracker:
    global _global_tracker
    with _global_mu:
        if _global_tracker is None:
            _global_tracker = RaceTracker()
        return _global_tracker


def reset_race_tracker(
    lockdep: Optional[LockDepTracker] = None,
) -> RaceTracker:
    """Fresh module-level tracker (test fixtures call this)."""
    global _global_tracker
    with _global_mu:
        _global_tracker = RaceTracker(lockdep=lockdep)
        return _global_tracker
