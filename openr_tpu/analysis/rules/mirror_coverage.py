"""``mirror-coverage``: every resident device buffer declares how it
heals.

The integrity plane (and the device-loss recover rung before it) can
only quarantine-and-heal an engine whose resident buffers are all
re-derivable: either a settle-on-success host mirror exists
(``_packed_dev`` ↔ ``_packed_host``) or a cold rebuild recipe does
(``_dr`` ← the band tensors / the LinkState). A resident buffer with
neither is unhealable state — the first silent flip or torn dispatch
strands the engine in quarantine with nothing sound to rebuild from,
and nobody notices until that day.

This rule makes the declaration mandatory at review time: every
literal name registered via ``@resident_buffers(...)`` must appear as
a keyword of a ``@mirrored_by(...)`` on the same class, or carry an
audited in-source suppression (``# openr-lint:
disable=mirror-coverage -- reason``) explaining why the buffer is
legitimately unhealable (e.g. a derived scratch block a cold build
always regenerates wholesale).

Unlike ``sharding-spec`` this rule is TREE-WIDE — unhealable resident
state is a hazard wherever it lives, not just on the churn path — and
purely class-local, so it needs no cross-file collect pass.
"""

from __future__ import annotations

from typing import Iterable, List

from openr_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    decorator_info,
    literal_or_none,
)

RULE_ID = "mirror-coverage"


class MirrorCoverageRule(Rule):
    id = RULE_ID
    description = (
        "every @resident_buffers name must appear in a @mirrored_by "
        "declaration on the same class (or carry an audited "
        "suppression) — a resident with no mirror and no rebuild "
        "recipe is unhealable after corruption or device loss"
    )

    def check(self, sf: SourceFile, ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in sf.classes():
            residents = []  # (name, anchor node)
            mirrored = set()
            for dec in cls.decorator_list:
                name, call = decorator_info(dec)
                if name is None or call is None:
                    continue
                leaf = name.split(".")[-1]
                if leaf == "resident_buffers":
                    for arg in call.args:
                        val = literal_or_none(arg)
                        if isinstance(val, str):
                            residents.append((val, arg))
                elif leaf == "mirrored_by":
                    mirrored.update(
                        kw.arg for kw in call.keywords if kw.arg
                    )
            for buf, node in residents:
                if buf in mirrored:
                    continue
                findings.append(
                    Finding(
                        self.id, sf.path, node.lineno, node.col_offset,
                        f"resident buffer {buf!r} on {cls.name} has no "
                        "@mirrored_by entry: declare its host mirror "
                        "or rebuild recipe, or suppress with an "
                        "audited reason — otherwise the integrity "
                        "plane can quarantine this engine but never "
                        "heal it",
                    )
                )
        return findings
