"""``retrace-risk``: static args to jitted dispatches that break or
thrash the trace cache.

``jax.jit`` keys its compilation cache on the *values* of static
arguments. Two failure modes, both invisible until production:

- an **unhashable** static value (list / dict / set / comprehension)
  raises at trace time — but only on the first call of that code path,
  which may be the overflow rung of the retry ladder rather than
  anything a smoke test exercises;
- a **call-varying** static value (fresh lambda, ``time.*()``,
  ``id()``, RNG draws) is a new cache key every call — a silent
  retrace storm that turns the microseconds-long churn step into a
  milliseconds-long compile, exactly the regression PR 1 existed to
  remove.

The rule resolves jitted defs (``@jax.jit`` /
``@functools.partial(jax.jit, static_argnums=...)``) to their static
parameter names during collect, then classifies the expressions flowing
into static positions at every call site. It also flags ``jax.jit(...)``
wrapper construction inside a loop body — each iteration makes a fresh
wrapper with an empty cache.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from openr_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    call_kwarg,
    decorator_info,
    dotted_name,
    literal_or_none,
)

RULE_ID = "retrace-risk"

_UNHASHABLE = (
    ast.List,
    ast.Dict,
    ast.Set,
    ast.ListComp,
    ast.DictComp,
    ast.SetComp,
    ast.GeneratorExp,
)
_CALL_VARYING = {
    "time.time",
    "time.perf_counter",
    "time.monotonic",
    "time.time_ns",
    "id",
    "object",
    "random.random",
    "random.randint",
    "uuid.uuid4",
}


def _params(fn: ast.AST) -> List[str]:
    args = fn.args
    return [a.arg for a in args.posonlyargs + args.args]


def _classify(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, _UNHASHABLE):
        return f"unhashable {type(expr).__name__.lower()} literal"
    if isinstance(expr, ast.Lambda):
        return "fresh lambda (new cache key every call)"
    if isinstance(expr, ast.Call):
        callee = dotted_name(expr.func)
        if callee in _CALL_VARYING:
            return f"call-varying value {callee}()"
    if isinstance(expr, ast.Tuple):
        for elt in expr.elts:
            hit = _classify(elt)
            if hit is not None:
                return hit
    return None


class RetraceRiskRule(Rule):
    id = RULE_ID
    description = (
        "static args to jitted functions must be hashable and stable "
        "across calls; jit wrappers must not be built inside loops"
    )

    def collect(self, sf: SourceFile, ctx: AnalysisContext) -> None:
        store = ctx.scratch(self.id)
        jitted: Dict[str, Dict[str, object]] = store.setdefault("jitted", {})
        for fn, _cls in sf.functions():
            for dec in fn.decorator_list:
                name, call = decorator_info(dec)
                if name is None or name.split(".")[-1] != "jit":
                    continue
                params = _params(fn)
                static: Set[str] = set()
                if call is not None:
                    nums = literal_or_none(
                        call_kwarg(call, "static_argnums")
                    )
                    if isinstance(nums, int):
                        nums = (nums,)
                    if isinstance(nums, (tuple, list)):
                        for i in nums:
                            if isinstance(i, int) and i < len(params):
                                static.add(params[i])
                    names = literal_or_none(
                        call_kwarg(call, "static_argnames")
                    )
                    if isinstance(names, str):
                        names = (names,)
                    if isinstance(names, (tuple, list)):
                        static.update(
                            n for n in names if isinstance(n, str)
                        )
                if static:
                    jitted[fn.name] = {"params": params, "static": static}

    def check(self, sf: SourceFile, ctx: AnalysisContext) -> Iterable[Finding]:
        jitted = ctx.scratch(self.id).get("jitted", {})
        findings: List[Finding] = []
        assert sf.tree is not None

        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None:
                continue
            info = jitted.get(callee.split(".")[-1])
            if info is None:
                continue
            params: List[str] = info["params"]  # type: ignore[assignment]
            static: Set[str] = info["static"]  # type: ignore[assignment]
            for i, arg in enumerate(node.args):
                pname = params[i] if i < len(params) else None
                if pname in static:
                    findings.extend(
                        self._flag(sf, node, arg, pname, callee)
                    )
            for kw in node.keywords:
                if kw.arg in static:
                    findings.extend(
                        self._flag(sf, node, kw.value, kw.arg, callee)
                    )

        # jit wrapper construction inside a loop body
        for loop in ast.walk(sf.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in ast.walk(loop):
                if isinstance(node, ast.Call):
                    name = dotted_name(node.func)
                    if name in ("jax.jit", "jit"):
                        findings.append(
                            Finding(
                                self.id,
                                sf.path,
                                node.lineno,
                                node.col_offset,
                                "jax.jit wrapper constructed inside a "
                                "loop — every iteration starts with an "
                                "empty trace cache; hoist the wrapper "
                                "out (or key a persistent cache on the "
                                "static shape)",
                            )
                        )
        return findings

    def _flag(
        self, sf: SourceFile, call: ast.Call, arg: ast.expr,
        pname: str, callee: str,
    ) -> Iterable[Finding]:
        hit = _classify(arg)
        if hit is not None:
            yield Finding(
                self.id,
                sf.path,
                call.lineno,
                call.col_offset,
                f"{hit} passed as static parameter '{pname}' of "
                f"{callee} — static args are trace-cache keys and must "
                "be hashable and call-stable",
            )
