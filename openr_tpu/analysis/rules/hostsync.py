"""``host-sync-in-window``: no device->host sync inside a solve window.

PR 1 closed the 10x host-overhead gap precisely by removing blocking
readbacks from the churn path; PR 3 then made the one remaining
readback double-buffered. This rule keeps it that way: inside any
function annotated ``@solve_window`` — i.e. code that runs between a
churn dispatch and its commit — the following forms are flagged:

- ``np.asarray(...)`` / ``numpy.asarray(...)`` / ``np.array(...)`` on
  anything (forces a transfer when handed a device array; a host-list
  conversion is a legitimate suppression with a reason),
- ``jax.device_get(...)`` / ``device_get(...)``,
- ``<expr>.block_until_ready()``,
- ``float(...)`` / ``int(...)`` / ``bool(...)`` applied to an
  expression that mentions a device-resident name (``*_dev`` attrs,
  ``_dr``) — scalar coercion of an Array is an implicit
  ``device_get``,
- ``.item()`` / ``.tolist()`` on such device-ish expressions.

The rule is syntactic; only the annotated function's own body is
scanned (nested defs get their own annotation if they need it), so a
``@solve_window`` marker is a precise, reviewable claim.

PR 13 added the committed-dispatch contract on top: one SUBMIT and one
REAP per event window, with every crossing routed through
``ops.dispatch_accounting`` (``count_dispatch`` / ``kick_async`` /
``reap_read``). ``CommittedDispatchRule`` (id ``committed-dispatch``,
same module so the two window disciplines share one classifier) scans
``@committed_dispatch`` bodies for the raw sync forms above — a raw
``jax.device_get`` or ``.block_until_ready()`` between submit and reap
is an unaccounted host round trip. One deliberate difference: the
``np.asarray``-family calls are flagged only when their argument
mentions a device-resident name — committed bodies legitimately do
host-side numpy patch prep between reaps, and a host-list conversion
breaks nothing."""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from openr_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    decorator_info,
    dotted_name,
)

RULE_ID = "host-sync-in-window"

_SYNC_CALLS = {
    "np.asarray",
    "numpy.asarray",
    "np.array",
    "numpy.array",
    "jax.device_get",
    "device_get",
}
_COERCIONS = {"float", "int", "bool"}
_SYNC_METHODS = {"block_until_ready", "item", "tolist"}
_DEVICE_HINTS = ("_dr",)


def _mentions_device(expr: ast.expr) -> Optional[str]:
    for sub in ast.walk(expr):
        name = None
        if isinstance(sub, ast.Attribute):
            name = sub.attr
        elif isinstance(sub, ast.Name):
            name = sub.id
        if name is not None and (
            name in _DEVICE_HINTS or name.endswith("_dev")
        ):
            return name
    return None


def _has_decorator(fn: ast.AST, marker: str) -> bool:
    for dec in fn.decorator_list:
        name, _call = decorator_info(dec)
        if name is not None and name.split(".")[-1] == marker:
            return True
    return False


def _is_solve_window(fn: ast.AST) -> bool:
    return _has_decorator(fn, "solve_window")


def _own_body_walk(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk the function body but do not descend into nested function
    or class definitions — they make their own solve-window claim."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


class HostSyncInWindowRule(Rule):
    id = RULE_ID
    description = (
        "no blocking device->host transfer inside @solve_window code"
    )

    def check(self, sf: SourceFile, ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn, _cls in sf.functions():
            if not _is_solve_window(fn):
                continue
            for node in _own_body_walk(fn):
                hit = self._classify(node)
                if hit is not None:
                    findings.append(
                        Finding(
                            self.id,
                            sf.path,
                            node.lineno,
                            node.col_offset,
                            f"{hit} inside @solve_window '{fn.name}' — "
                            "blocking device->host sync serializes the "
                            "solve pipeline; stage it through the "
                            "deferred readback instead",
                        )
                    )
        return findings

    def _classify(self, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        callee = dotted_name(node.func)
        if callee in _SYNC_CALLS:
            return f"{callee}()"
        if (
            callee in _COERCIONS
            and node.args
            and _mentions_device(node.args[0]) is not None
        ):
            dev = _mentions_device(node.args[0])
            return f"{callee}() scalar coercion of device value '{dev}'"
        if isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            if meth == "block_until_ready":
                return ".block_until_ready()"
            if meth in ("item", "tolist") and (
                _mentions_device(node.func.value) is not None
            ):
                dev = _mentions_device(node.func.value)
                return f".{meth}() on device value '{dev}'"
        return None


_ASARRAY_FAMILY = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
}


class CommittedDispatchRule(HostSyncInWindowRule):
    """``committed-dispatch``: inside ``@committed_dispatch`` bodies
    the host may cross the device boundary only through the
    ``ops.dispatch_accounting`` helpers — any raw sync form between
    submit and reap serializes the committed event window."""

    id = "committed-dispatch"
    description = (
        "no raw device->host sync between submit and reap in "
        "@committed_dispatch event-path code (use "
        "dispatch_accounting.reap_read / kick_async)"
    )

    def check(self, sf: SourceFile, ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn, _cls in sf.functions():
            if not _has_decorator(fn, "committed_dispatch"):
                continue
            for node in _own_body_walk(fn):
                hit = self._classify(node)
                if hit is not None:
                    findings.append(
                        Finding(
                            self.id,
                            sf.path,
                            node.lineno,
                            node.col_offset,
                            f"{hit} inside @committed_dispatch "
                            f"'{fn.name}' — a raw host round trip "
                            "between submit and reap; route it "
                            "through dispatch_accounting.reap_read "
                            "(or kick_async + reap_read(kicked=True))",
                        )
                    )
        return findings

    def _classify(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            if callee in _ASARRAY_FAMILY:
                # host-list prep between reaps is legitimate on the
                # event path; only a device operand forces a transfer
                if not (
                    node.args
                    and _mentions_device(node.args[0]) is not None
                ):
                    return None
        return super()._classify(node)


def _assigned_names(target: ast.expr) -> Iterable[str]:
    """Plain names bound by an assignment target. Attribute and
    subscript stores are skipped — ``self.meta = reap_read(...)``
    binds the attribute, and tainting the whole object would flag
    every later ``if self...``."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _assigned_names(elt)
    elif isinstance(target, ast.Starred):
        yield from _assigned_names(target.value)


def _mentions_any(expr: ast.expr, names: set) -> Optional[str]:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Name) and sub.id in names:
            return sub.id
    return None


class HostBranchInChainRule(Rule):
    """``host-branch-in-chain``: control flow inside the committed
    chain must not fork on a meta readback. PR 16 moved the
    frontier-vs-full-width and overflow decisions into on-device
    seed-select branches precisely so a pipelined burst never breaks
    the fused chain on a 16-byte readback; an ``if``/``while`` whose
    test derives from a ``reap_read`` value reintroduces the stall —
    the host must materialize the meta row before it can even decide
    what to submit next. Sites that are deliberately host-side (the
    widened-layout split path, the bucket-ladder overflow check)
    carry audited suppressions."""

    id = "host-branch-in-chain"
    description = (
        "no if/while on meta-readback values inside "
        "@committed_dispatch/@solve_window bodies (move the decision "
        "on device or suppress with a reason)"
    )

    def check(self, sf: SourceFile, ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn, _cls in sf.functions():
            if not (
                _has_decorator(fn, "committed_dispatch")
                or _has_decorator(fn, "solve_window")
            ):
                continue
            tainted = self._tainted_names(fn)
            if not tainted:
                continue
            for node in _own_body_walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                name = _mentions_any(node.test, tainted)
                if name is None:
                    continue
                kind = "if" if isinstance(node, ast.If) else "while"
                findings.append(
                    Finding(
                        self.id,
                        sf.path,
                        node.lineno,
                        node.col_offset,
                        f"{kind} on meta-readback value '{name}' "
                        f"inside '{fn.name}' — a host branch in the "
                        "committed chain serializes the pipeline; "
                        "fold the decision into the fused executable "
                        "(seed-select / lax.cond) or suppress with "
                        "an audited reason",
                    )
                )
        return findings

    @staticmethod
    def _tainted_names(fn: ast.AST) -> set:
        """Names bound (directly or one hop transitively) from a
        ``reap_read(...)`` call in the function's own body. The
        fixpoint over assignments catches ``cnt = int(reap_read(m))``
        as well as ``rows = meta[0]`` after ``meta = reap_read(...)``."""
        tainted: set = set()
        assigns: List[Tuple[List[str], ast.expr]] = []
        for node in _own_body_walk(fn):
            if isinstance(node, ast.Assign):
                targets = [
                    n for t in node.targets for n in _assigned_names(t)
                ]
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = list(_assigned_names(node.target))
                value = node.value
            else:
                continue
            assigns.append((targets, value))
            for sub in ast.walk(value):
                if isinstance(sub, ast.Call):
                    callee = dotted_name(sub.func)
                    if callee is not None and (
                        callee.split(".")[-1] == "reap_read"
                    ):
                        tainted.update(targets)
                        break
        changed = True
        while changed:
            changed = False
            for targets, value in assigns:
                if _mentions_any(value, tainted) is None:
                    continue
                fresh = [t for t in targets if t not in tainted]
                if fresh:
                    tainted.update(fresh)
                    changed = True
        return tainted
