"""``donation-hazard``: resident device buffers vs donating dispatches.

The churn path's correctness hinges on a buffer-lifetime discipline
that jax will not check for you:

- a RESIDENT buffer (``@resident_buffers`` attribute, ``_packed_dev``
  style) must never flow into a ``donate_argnums`` position of a jitted
  dispatch that can re-run against the same inputs — the route engine's
  overflow retry ladder re-dispatches at a larger bucket against the
  SAME resident arrays, so a donated resident is freed memory on the
  second rung (silent wrong routes or a crash, depending on backend);
- a value donated into a dispatch must not be read afterwards in the
  same function (donation invalidates the buffer);
- a cold rebuild (``@requires_drain``) must drain the in-flight
  ``PendingDelta`` before replacing resident buffers, or a caller-held
  handle resolves against freed device state;
- a ``@fault_boundary`` function (a degradation-ladder rung) must not
  donate ANY argument, resident or not: when a rung fails the
  supervisor walks on to the next rung against the same inputs, so a
  buffer donated by a failed dispatch is freed memory for every deeper
  rung. This holds by construction — the annotation marks the re-run
  contract, no suppression needed for the safe (donation-free) shape.

Detection is name-based and alias-tainting: a local bound from a
resident attribute carries the taint into call arguments. Donating
callables are found two ways: jitted defs whose decorator carries
``donate_argnums``/``donate_argnames``, and plain wrappers annotated
``@donates("param", ...)`` (the cross-module escape hatch — wrappers
forward into jitted donators the checker already understands).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from openr_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    call_kwarg,
    decorator_info,
    dotted_name,
    literal_or_none,
    unwrap_aot_call,
)

RULE_ID = "donation-hazard"

#: attribute spellings that are resident by convention even without an
#: explicit ``@resident_buffers`` registration (the ``_*_dev`` style
#: plus the engines' resident distance matrix)
_DEFAULT_RESIDENT = ("_dr",)


def _is_resident_name(attr: str, registered: Set[str]) -> bool:
    return (
        attr in registered
        or attr in _DEFAULT_RESIDENT
        or (attr.startswith("_") and attr.endswith("_dev"))
    )


def _params(fn: ast.AST) -> List[str]:
    args = fn.args
    return [a.arg for a in args.posonlyargs + args.args]


def _is_fault_boundary(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        name, _call = decorator_info(dec)
        if name and name.split(".")[-1] == "fault_boundary":
            return True
    return False


def _branch_contexts(fn: ast.AST) -> Dict[int, Tuple[Tuple[int, bool], ...]]:
    """line -> chain of (If-node id, branch) enclosing it, so the
    read-after-donation check can skip pairs on mutually exclusive
    paths (donation in the ``elif``, read in the ``else``)."""
    ctx_of: Dict[int, Tuple[Tuple[int, bool], ...]] = {}

    def mark(node: ast.AST, ctx: Tuple[Tuple[int, bool], ...]) -> None:
        end = getattr(node, "end_lineno", node.lineno) or node.lineno
        for ln in range(node.lineno, end + 1):
            ctx_of.setdefault(ln, ctx)

    def walk(stmts: List[ast.stmt], ctx: Tuple[Tuple[int, bool], ...]) -> None:
        for s in stmts:
            if isinstance(s, ast.If):
                mark(s.test, ctx)
                walk(s.body, ctx + ((id(s), True),))
                walk(s.orelse, ctx + ((id(s), False),))
            elif isinstance(s, ast.Try):
                walk(s.body, ctx)
                for h in s.handlers:
                    walk(h.body, ctx)
                walk(s.orelse, ctx)
                walk(s.finalbody, ctx)
            elif isinstance(
                s, (ast.For, ast.AsyncFor, ast.While, ast.With, ast.AsyncWith)
            ):
                walk(s.body, ctx)
                walk(getattr(s, "orelse", []) or [], ctx)
            else:
                mark(s, ctx)

    walk(fn.body, ())
    return ctx_of


def _exclusive(
    a: Tuple[Tuple[int, bool], ...], b: Tuple[Tuple[int, bool], ...]
) -> bool:
    """True when the two contexts sit in different branches of the same
    If — they cannot execute on one path."""
    da = dict(a)
    return any(da.get(k, v) != v for k, v in b)


class DonationHazardRule(Rule):
    id = RULE_ID
    description = (
        "resident buffers must not be donated, donated values must not "
        "be read back, and cold rebuilds must drain the pending delta"
    )

    # -- collect: donating callables + resident attrs ----------------

    def collect(self, sf: SourceFile, ctx: AnalysisContext) -> None:
        store = ctx.scratch(self.id)
        donators: Dict[str, Dict[str, object]] = store.setdefault(
            "donators", {}
        )
        resident: Set[str] = store.setdefault("resident", set())
        drains: List[Tuple[SourceFile, ast.AST, str]] = store.setdefault(
            "drains", []
        )

        for cls in sf.classes():
            for dec in cls.decorator_list:
                name, call = decorator_info(dec)
                if name and name.split(".")[-1] == "resident_buffers" and call:
                    for arg in call.args:
                        val = literal_or_none(arg)
                        if isinstance(val, str):
                            resident.add(val)

        for fn, _cls in sf.functions():
            params = _params(fn)
            donated: Set[str] = set()
            for dec in fn.decorator_list:
                name, call = decorator_info(dec)
                if name is None:
                    continue
                leaf = name.split(".")[-1]
                if leaf == "jit" and call is not None:
                    nums = literal_or_none(call_kwarg(call, "donate_argnums"))
                    if isinstance(nums, int):
                        nums = (nums,)
                    if isinstance(nums, (tuple, list)):
                        for i in nums:
                            if isinstance(i, int) and i < len(params):
                                donated.add(params[i])
                    names = literal_or_none(
                        call_kwarg(call, "donate_argnames")
                    )
                    if isinstance(names, str):
                        names = (names,)
                    if isinstance(names, (tuple, list)):
                        donated.update(n for n in names if isinstance(n, str))
                elif leaf == "donates" and call is not None:
                    for arg in call.args:
                        val = literal_or_none(arg)
                        if isinstance(val, str):
                            donated.add(val)
                elif leaf == "requires_drain" and call is not None:
                    drain = literal_or_none(call.args[0]) if call.args else None
                    if isinstance(drain, str):
                        drains.append((sf, fn, drain))
            if donated:
                donators[fn.name] = {
                    "params": params,
                    "donated": donated,
                    "path": sf.path,
                }

    # -- check: call sites + drain ordering --------------------------

    def check(self, sf: SourceFile, ctx: AnalysisContext) -> Iterable[Finding]:
        store = ctx.scratch(self.id)
        donators = store.get("donators", {})
        resident: Set[str] = store.get("resident", set())
        findings: List[Finding] = []

        for fn, _cls in sf.functions():
            findings.extend(
                self._check_function(sf, fn, donators, resident)
            )
        for dsf, dfn, drain in store.get("drains", []):
            if dsf is sf:
                findings.extend(
                    self._check_drain(sf, dfn, drain, resident)
                )
        return findings

    def _check_function(
        self,
        sf: SourceFile,
        fn: ast.AST,
        donators: Dict[str, Dict[str, object]],
        resident: Set[str],
    ) -> Iterable[Finding]:
        findings: List[Finding] = []
        fault_boundary = _is_fault_boundary(fn)
        # taint: local names bound (anywhere in the function) from a
        # resident attribute — conservative, no flow sensitivity
        tainted: Dict[str, str] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Attribute
            ):
                if _is_resident_name(node.value.attr, resident):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            tainted[tgt.id] = node.value.attr

        # donated expressions seen, keyed for read-after-donation:
        # ("name", x) for locals, ("attr", a) for self/obj attributes
        donated_sites: List[Tuple[Tuple[str, str], int]] = []

        def resident_attr_in(expr: ast.expr) -> Optional[str]:
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Attribute) and _is_resident_name(
                    sub.attr, resident
                ):
                    return sub.attr
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    return f"{sub.id} (= self.{tainted[sub.id]})"
            return None

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None:
                continue
            call_args = node.args
            call_keywords = node.keywords
            aot = unwrap_aot_call(node)
            if aot is not None:
                # dispatch behind the AOT executable cache: check the
                # wrapped fn's signature against the dyn-arg tuple
                callee, call_args = aot
                call_keywords = []
            info = donators.get(callee.split(".")[-1])
            if info is None:
                continue
            params: List[str] = info["params"]  # type: ignore[assignment]
            donated: Set[str] = info["donated"]  # type: ignore[assignment]
            for i, arg in enumerate(call_args):
                pname = params[i] if i < len(params) else None
                if pname not in donated:
                    continue
                findings.extend(
                    self._flag_donated_arg(
                        sf, fn, node, arg, pname, callee,
                        resident_attr_in, donated_sites,
                        fault_boundary,
                    )
                )
            for kw in call_keywords:
                if kw.arg in donated:
                    findings.extend(
                        self._flag_donated_arg(
                            sf, fn, node, kw.value, kw.arg, callee,
                            resident_attr_in, donated_sites,
                            fault_boundary,
                        )
                    )

        # read-after-donation: any Load of a donated name/attr after
        # the donating call line, with no intervening re-assignment
        stores: Dict[Tuple[str, str], List[int]] = {}
        loads: Dict[Tuple[str, str], List[int]] = {}
        for node in ast.walk(fn):
            key = None
            if isinstance(node, ast.Name):
                key = ("name", node.id)
            elif isinstance(node, ast.Attribute):
                key = ("attr", node.attr)
            if key is None:
                continue
            if isinstance(node.ctx, ast.Store):
                stores.setdefault(key, []).append(node.lineno)
            elif isinstance(node.ctx, ast.Load):
                loads.setdefault(key, []).append(node.lineno)
        branch_ctx = _branch_contexts(fn)
        for key, call_line in donated_sites:
            # call_line is the donating call's END line: loads that are
            # lexically part of the (possibly multiline) call are the
            # donation itself, not a read-after
            # a store ON the call's end line is the idiomatic
            # consume-and-rebind (`buf = consume(buf, x)`): it cuts off
            # the read-after window just like a later rebind does
            rebind = min(
                (ln for ln in stores.get(key, []) if ln >= call_line),
                default=None,
            )
            for ln in loads.get(key, []):
                if ln > call_line and (rebind is None or ln < rebind):
                    if _exclusive(
                        branch_ctx.get(call_line, ()),
                        branch_ctx.get(ln, ()),
                    ):
                        continue
                    findings.append(
                        Finding(
                            self.id, sf.path, ln, 0,
                            f"'{key[1]}' read after being donated at "
                            f"line {call_line} (donation invalidates "
                            "the buffer)",
                        )
                    )
                    break
        return findings

    def _flag_donated_arg(
        self, sf, fn, call, arg, pname, callee, resident_attr_in,
        donated_sites, fault_boundary=False,
    ) -> Iterable[Finding]:
        findings: List[Finding] = []
        hit = resident_attr_in(arg)
        if hit is not None:
            findings.append(
                Finding(
                    self.id, sf.path, call.lineno, call.col_offset,
                    f"resident buffer {hit} flows into donated "
                    f"parameter '{pname}' of {callee} — the dispatch "
                    "frees it while the resident state still "
                    "references it (retry-ladder hazard)",
                )
            )
        elif fault_boundary:
            findings.append(
                Finding(
                    self.id, sf.path, call.lineno, call.col_offset,
                    f"@fault_boundary function {fn.name} donates "
                    f"parameter '{pname}' into {callee} — if this rung "
                    "fails, the supervisor re-runs deeper rungs against "
                    "the same inputs, which the donation just freed",
                )
            )
        end = getattr(call, "end_lineno", call.lineno) or call.lineno
        if isinstance(arg, ast.Name):
            donated_sites.append((("name", arg.id), end))
        elif isinstance(arg, ast.Attribute):
            donated_sites.append((("attr", arg.attr), end))
        return findings

    def _check_drain(
        self, sf: SourceFile, fn: ast.AST, drain: str, resident: Set[str]
    ) -> Iterable[Finding]:
        drain_line = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func)
                if callee is not None and callee.split(".")[-1] == drain:
                    drain_line = (
                        node.lineno
                        if drain_line is None
                        else min(drain_line, node.lineno)
                    )
        first_write = None
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Store)
                and _is_resident_name(node.attr, resident)
            ):
                first_write = (
                    node.lineno
                    if first_write is None
                    else min(first_write, node.lineno)
                )
        if drain_line is None:
            yield Finding(
                self.id, sf.path, fn.lineno, fn.col_offset,
                f"{fn.name} is @requires_drain('{drain}') but never "
                f"calls {drain}() — a caller-held PendingDelta would "
                "dangle over the replaced resident state",
            )
        elif first_write is not None and first_write < drain_line:
            yield Finding(
                self.id, sf.path, first_write, 0,
                f"{fn.name} writes a resident buffer before calling "
                f"{drain}() (line {drain_line}) — drain the pending "
                "delta first",
            )
