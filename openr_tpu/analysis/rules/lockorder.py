"""``lock-order``: static lock-acquisition graph, cycle = finding.

The per-module threads (kvstore sync loop, messaging replicators,
telemetry scrapers, decision debounce) each own locks; a deadlock needs
only two of them acquired in opposite orders on two threads. This rule
builds the whole-tree *may-acquire* graph and reports:

- **cycles** in the acquired-while-holding edge relation (each edge
  carries its first witness site, so the report names both halves of
  the inversion), and
- **self-edges on non-reentrant locks** — ``threading.Lock`` acquired
  while already held on the same path (``RLock`` self-edges are the
  reentrant design and allowed).

Model (syntactic, conservative):

- a *lock class* is ``self._x = threading.Lock() | RLock() |
  Condition(...)`` anywhere in a class body; its identity is
  ``ClassName._x`` (instance-insensitive, like kernel lockdep classes).
  ``Condition(self._lock)`` aliases the underlying lock;
  bare ``Condition()`` owns an internal RLock.
- acquisitions are ``with <lockexpr>:`` regions and explicit
  ``<lockexpr>.acquire()`` calls.
- while a region holds lock A, any call whose *may-acquire* set
  (transitive, fixpoint over the call graph) contains B adds edge
  A -> B. Receivers resolve through: ``self`` methods, attribute types
  recorded from constructor assignments (``self._q = RQueue(...)``),
  parameter annotations, and return annotations
  (``get_registry() -> Registry``).

The runtime companion (:mod:`openr_tpu.analysis.lockdep`) catches the
dynamic orders this over-approximation cannot see.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from openr_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    dotted_name,
)

RULE_ID = "lock-order"

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond"}


class _Model:
    """Whole-tree facts accumulated during collect."""

    def __init__(self) -> None:
        # lock id ("Class._attr") -> "lock" | "rlock"
        self.locks: Dict[str, str] = {}
        # (class, attr) -> lock id (identity map + Condition aliases)
        self.attr_lock: Dict[Tuple[str, str], str] = {}
        # (class, attr) -> type name, from constructor-style assigns
        self.attr_type: Dict[Tuple[str, str], str] = {}
        # function leaf name -> return-annotation type name
        self.returns: Dict[str, str] = {}
        # (class | None, func name) -> (ast node, SourceFile)
        self.methods: Dict[Tuple[Optional[str], str], Tuple[ast.AST, SourceFile]] = {}
        self.class_names: Set[str] = set()


def _ann_name(ann: Optional[ast.expr]) -> Optional[str]:
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.split(".")[-1].split("[")[0]
    name = dotted_name(ann)
    return name.split(".")[-1] if name else None


class LockOrderRule(Rule):
    id = RULE_ID
    description = (
        "lock acquisition order must be acyclic across threads; "
        "non-reentrant locks must not be re-acquired while held"
    )

    def collect(self, sf: SourceFile, ctx: AnalysisContext) -> None:
        model: _Model = ctx.scratch(self.id).setdefault("model", _Model())
        for cls in sf.classes():
            model.class_names.add(cls.name)
        for fn, cls in sf.functions():
            key = (cls, fn.name)
            # outermost definition wins; nested dupes are rare and
            # conservative either way
            model.methods.setdefault(key, (fn, sf))
            rname = _ann_name(getattr(fn, "returns", None))
            if rname is not None:
                model.returns.setdefault(fn.name, rname)
            if cls is None:
                continue
            for node in ast.walk(fn):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == "self"
                ):
                    continue
                attr = node.targets[0].attr
                value = node.value
                if not isinstance(value, ast.Call):
                    continue
                callee = dotted_name(value.func)
                if callee is None:
                    continue
                leaf = callee.split(".")[-1]
                if leaf in _LOCK_CTORS:
                    if leaf == "Condition":
                        # Condition(self._lock) aliases that lock;
                        # Condition() owns an internal RLock
                        if (
                            value.args
                            and isinstance(value.args[0], ast.Attribute)
                            and isinstance(value.args[0].value, ast.Name)
                            and value.args[0].value.id == "self"
                        ):
                            model.attr_lock[(cls, attr)] = (
                                f"{cls}.{value.args[0].attr}"
                            )
                            continue
                        lock_id = f"{cls}.{attr}"
                        model.locks[lock_id] = "rlock"
                        model.attr_lock[(cls, attr)] = lock_id
                    else:
                        lock_id = f"{cls}.{attr}"
                        model.locks[lock_id] = _LOCK_CTORS[leaf]
                        model.attr_lock[(cls, attr)] = lock_id
                else:
                    # constructor-style receiver typing
                    model.attr_type.setdefault((cls, attr), leaf)

    # -- finalize: resolve, fixpoint, walk, report -------------------

    def finalize(self, ctx: AnalysisContext) -> Iterable[Finding]:
        model: Optional[_Model] = ctx.scratch(self.id).get("model")
        if model is None:
            return ()
        # prune attr_type entries that aren't known classes (e.g.
        # self._x = dict(...)), so resolution stays precise
        model.attr_type = {
            k: v
            for k, v in model.attr_type.items()
            if v in model.class_names
        }
        model.returns = {
            k: v for k, v in model.returns.items() if v in model.class_names
        }

        direct: Dict[Tuple[Optional[str], str], Set[str]] = {}
        calls: Dict[
            Tuple[Optional[str], str], Set[Tuple[Optional[str], str]]
        ] = {}
        walkers: Dict[Tuple[Optional[str], str], "_MethodWalk"] = {}
        for key, (fn, sf) in model.methods.items():
            w = _MethodWalk(model, key[0], fn, sf)
            w.run()
            walkers[key] = w
            direct[key] = set(w.acquired)
            calls[key] = {c for c in w.called if c in model.methods}

        # may-acquire fixpoint
        may: Dict[Tuple[Optional[str], str], Set[str]] = {
            k: set(v) for k, v in direct.items()
        }
        changed = True
        while changed:
            changed = False
            for key in may:
                for callee in calls.get(key, ()):
                    before = len(may[key])
                    may[key] |= may.get(callee, set())
                    if len(may[key]) != before:
                        changed = True

        # edges: lock held -> lock acquired, with first witness
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self_edges: List[Tuple[str, str, int, str]] = []
        for key, w in walkers.items():
            for held, inner, line, desc in w.nested:
                self._add_edge(
                    model, edges, self_edges, held, inner,
                    w.sf.path, line, desc,
                )
            for held, callee, line in w.calls_while_held:
                for inner in may.get(callee, ()):
                    self._add_edge(
                        model, edges, self_edges, held, inner,
                        w.sf.path, line,
                        f"via call to {callee[0] or '<module>'}."
                        f"{callee[1]}()",
                    )

        findings: List[Finding] = []
        for lock_id, path, line, desc in self_edges:
            findings.append(
                Finding(
                    self.id, path, line, 0,
                    f"non-reentrant lock {lock_id} acquired while "
                    f"already held ({desc}) — self-deadlock",
                )
            )
        for cycle in _find_cycles({e for e in edges}):
            # witness the cycle at its first edge's site
            first = edges[(cycle[0], cycle[1])]
            chain = " -> ".join(cycle + (cycle[0],))
            detail = "; ".join(
                f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
                for a, b in zip(cycle, cycle[1:] + (cycle[0],))
            )
            findings.append(
                Finding(
                    self.id, first[0], first[1], 0,
                    f"lock-order cycle {chain} ({detail}) — two "
                    "threads taking these in opposite order deadlock",
                )
            )
        return findings

    def _add_edge(self, model, edges, self_edges, held, inner, path, line, desc):
        if held == inner:
            if model.locks.get(held) == "lock":
                self_edges.append((held, path, line, desc))
            return
        edges.setdefault((held, inner), (path, line, desc))


class _MethodWalk:
    """Single-method traversal tracking the with-held lock stack."""

    def __init__(
        self, model: _Model, cls: Optional[str], fn: ast.AST, sf: SourceFile
    ) -> None:
        self.model = model
        self.cls = cls
        self.fn = fn
        self.sf = sf
        self.acquired: Set[str] = set()
        self.called: Set[Tuple[Optional[str], str]] = set()
        # (held, inner, line, desc) for directly nested acquisitions
        self.nested: List[Tuple[str, str, int, str]] = []
        # (held, callee key, line) for calls made while holding
        self.calls_while_held: List[Tuple[str, Tuple[Optional[str], str], int]] = []
        # local var -> class name (from annotated params + typed calls)
        self.var_type: Dict[str, str] = {}

    def run(self) -> None:
        args = self.fn.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            t = _ann_name(a.annotation)
            if t is not None and t in self.model.class_names:
                self.var_type[a.arg] = t
        # one pre-pass for local typing: v = Ctor(...) / v = fn() with
        # a return annotation / v = self._attr of known type
        for node in ast.walk(self.fn):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                continue
            t = self._expr_type(node.value)
            if t is not None:
                self.var_type[node.targets[0].id] = t
        self._walk_body(self.fn.body, [])

    # -- resolution helpers ------------------------------------------

    def _expr_type(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Call):
            callee = dotted_name(expr.func)
            if callee is not None:
                leaf = callee.split(".")[-1]
                if leaf in self.model.class_names:
                    return leaf
                if leaf in self.model.returns:
                    return self.model.returns[leaf]
        elif isinstance(expr, ast.Attribute):
            owner = self._receiver_type(expr.value)
            if owner is not None:
                return self.model.attr_type.get((owner, expr.attr))
        return None

    def _receiver_type(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return self.cls
            return self.var_type.get(expr.id)
        if isinstance(expr, ast.Attribute):
            owner = self._receiver_type(expr.value)
            if owner is not None:
                return self.model.attr_type.get((owner, expr.attr))
        if isinstance(expr, ast.Call):
            return self._expr_type(expr)
        return None

    def _lock_id(self, expr: ast.expr) -> Optional[str]:
        """Resolve an expression used as a context manager / acquire
        receiver to a lock class id, or None."""
        if isinstance(expr, ast.Attribute):
            owner = self._receiver_type(expr.value)
            if owner is not None:
                return self.model.attr_lock.get((owner, expr.attr))
        return None

    def _callee_key(self, call: ast.Call) -> Optional[Tuple[Optional[str], str]]:
        func = call.func
        if isinstance(func, ast.Name):
            key = (None, func.id)
            return key if key in self.model.methods else None
        if isinstance(func, ast.Attribute):
            owner = self._receiver_type(func.value)
            if owner is not None and (owner, func.attr) in self.model.methods:
                return (owner, func.attr)
        return None

    # -- traversal ----------------------------------------------------

    def _walk_body(self, body: List[ast.stmt], held: List[str]) -> None:
        for stmt in body:
            self._walk_stmt(stmt, held)

    def _walk_stmt(self, stmt: ast.stmt, held: List[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs analyzed as their own methods
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            entered: List[str] = []
            for item in stmt.items:
                lock = self._lock_id(item.context_expr)
                if lock is not None:
                    self.acquired.add(lock)
                    for h in held + entered:
                        self.nested.append(
                            (h, lock, stmt.lineno, f"with {lock}")
                        )
                    entered.append(lock)
                else:
                    self._scan_expr(item.context_expr, held)
            self._walk_body(stmt.body, held + entered)
            return
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.stmt):
                self._walk_stmt(node, held)
            elif isinstance(node, ast.expr):
                self._scan_expr(node, held)
            elif isinstance(node, ast.ExceptHandler):
                if node.type is not None:
                    self._scan_expr(node.type, held)
                self._walk_body(node.body, held)

    def _scan_expr(self, expr: ast.expr, held: List[str]) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            # explicit .acquire()
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
            ):
                lock = self._lock_id(node.func.value)
                if lock is not None:
                    self.acquired.add(lock)
                    for h in held:
                        self.nested.append(
                            (h, lock, node.lineno, f"{lock}.acquire()")
                        )
                    continue
            key = self._callee_key(node)
            if key is not None:
                self.called.add(key)
                if held:
                    for h in held:
                        self.calls_while_held.append((h, key, node.lineno))


def _find_cycles(edges: Set[Tuple[str, str]]) -> List[Tuple[str, ...]]:
    """Minimal simple cycles via SCC then one cycle per SCC (enough to
    surface the inversion; the witness detail names every edge)."""
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())

    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        for w in graph[v]:
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in onstack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp: List[str] = []
            while True:
                w = stack.pop()
                onstack.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    cycles: List[Tuple[str, ...]] = []
    for comp in sccs:
        comp_set = set(comp)
        # walk one cycle inside the SCC deterministically
        start = min(comp)
        path = [start]
        seen = {start}
        cur = start
        while True:
            nxt = min(
                w for w in graph[cur] if w in comp_set
            )
            if nxt in seen:
                cycles.append(tuple(path[path.index(nxt):]))
                break
            path.append(nxt)
            seen.add(nxt)
            cur = nxt
    return cycles
