"""``shared-state``: cross-thread instance-attribute races, statically.

Open/R's design is many single-threaded modules, but the reproduction
has real cross-thread seams: the Decision emit executor, the
SolverService wave loop, KvStore's flood executor, netlink/UDP io
threads, ctrl server connection threads, tracer finish listeners,
registry gauge callbacks. The classic production killer on those seams
is a ``self._attr`` written on one thread and read on another with no
common lock. This rule convicts exactly that, whole-tree:

**Phase A — thread roles.** Every *entry point* that puts code on a
thread seeds a role:

- ``threading.Thread(target=X, name="...")`` — role is the literal
  thread name (or ``thread:Class.method`` when the name is dynamic);
  a target resolving to ``OpenrEventBase.run`` (or a subclass) is the
  event loop itself, role ``evb``.
- ``<executor>.submit(X)`` where the receiver was constructed as a
  ``ThreadPoolExecutor`` — role ``ex:Class._attr``.
- event-base marshalling and timers (``run_in_event_base``,
  ``call_and_wait``, ``schedule_timeout``, ``schedule_periodic``,
  ``add_queue_reader``) plus the constructor-registered callbacks
  (``AsyncDebounce``, ``AsyncThrottle``, ``PeriodicHandle``) — the
  callback runs on the loop thread, role ``evb``. All event bases
  share one role: cross-evb traffic goes through queues by design, and
  splitting the role per instance would convict same-thread pairs.
- registered listeners: ``add_finish_listener`` (role
  ``tracer.finish``), ``Registry.gauge(name, fn)`` (role
  ``registry.gauge`` — gauges are sampled from whatever thread
  snapshots the registry).
- ``@runs_on("ctrl")`` classes (the ctrl server dispatches handler
  methods by ``getattr`` on per-connection threads — invisible to the
  AST, so the handler classes declare it) and ``@thread_confined``
  -pinned methods.

Roles close over the call graph (caller -> callee fixpoint, receivers
resolved with the same typing machinery as ``lock-order``). A lambda
or function reference *passed into* a marshalling/registration call is
attributed to the TARGET role, not the enclosing method's role — the
``evb.call_and_wait(lambda: self._x)`` idiom reads ``_x`` on the loop
thread, not the caller's.

**Phase B — conviction.** For each instance attribute: a write outside
``__init__`` under role A and any access under role B != A, where the
two sites share no lock class (identity ``Class._attr``, shared with
``lock-order``; ``Condition(self._lock)`` aliases; a helper only ever
called with a lock held inherits that lock context), is a finding —
one per attribute, witnessed at the write.

Declared-safe escapes (``analysis.annotations``):

- ``@thread_confined(role, *attrs)`` — attrs only touched under one
  role (the runtime sanitizer can convict the claim if it lies);
- ``@guarded_by("Class._lock", *attrs)`` — always accessed under that
  lock, including paths the with-stack tracking cannot see;
- ``@handoff(*attrs)`` — publish-once-then-immutable;
- an audited ``# openr-lint: disable=shared-state -- why`` at the
  write site.

Known over-approximations (kept deliberately): methods no role
reaches never convict (unstarted code is silent, not noisy);
attributes holding locks, queues, executors and other internally
locked types are exempt; container mutator calls (``.add``,
``.append``, ``.update``...) on a self attribute count as writes;
dynamic dispatch beyond ``@runs_on`` is invisible. The runtime
companion (:mod:`openr_tpu.analysis.racedep`) watches the gap.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from openr_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    decorator_info,
    dotted_name,
)
from openr_tpu.analysis.rules.lockorder import (
    LockOrderRule,
    _ann_name,
    _MethodWalk,
    _Model,
)

RULE_ID = "shared-state"

#: event-base marshalling / timer APIs: the callable argument runs on
#: the loop thread (distinctive names — matched receiver-type-free so
#: untyped ``self._evb`` attributes still resolve)
_EVB_MARSHAL = {
    "run_in_event_base",
    "run_immediately_or_in_event_base",
    "call_and_wait",
    "schedule_timeout",
    "schedule_periodic",
    "add_queue_reader",
}

#: constructors that register their callback argument on an event base
_EVB_CTORS = {"AsyncDebounce", "AsyncThrottle", "PeriodicHandle"}

#: method-name -> role for listener registries
_LISTENER_ROLES = {
    "add_finish_listener": "tracer.finish",
    "gauge": "registry.gauge",
}

#: container/object mutator method names: a call on a self attribute
#: mutates the shared object behind it — counts as a write
_MUTATORS = {
    "add",
    "append",
    "appendleft",
    "clear",
    "discard",
    "extend",
    "insert",
    "pop",
    "popitem",
    "popleft",
    "remove",
    "setdefault",
    "update",
}

#: constructor type leafs that are internally synchronized (or are
#: synchronization primitives themselves) — their attrs never convict
_THREAD_SAFE_TYPES = {
    "Barrier",
    "BoundedSemaphore",
    "Event",
    "LifoQueue",
    "PriorityQueue",
    "Queue",
    "RQueue",
    "ReplicateQueue",
    "Semaphore",
    "SimpleQueue",
    "ThreadPoolExecutor",
    "TrackedLock",
    "local",
}

#: the decorators this rule reads (leaf names; analysis.annotations)
_ANN_THREAD_CONFINED = "thread_confined"
_ANN_GUARDED_BY = "guarded_by"
_ANN_HANDOFF = "handoff"
_ANN_RUNS_ON = "runs_on"

_EVB_ROLE = "evb"
_EVB_BASE = "OpenrEventBase"

_Key = Tuple[Optional[str], str]


@dataclass
class _Access:
    """One attribute touch, resolved to roles + effective lock set."""

    write: bool
    line: int
    path: str
    held: FrozenSet[str]
    roles: FrozenSet[str]
    in_init: bool


@dataclass
class _Extra:
    """Race-specific whole-tree facts (beyond lock-order's _Model)."""

    # class -> direct base names
    bases: Dict[str, List[str]] = field(default_factory=dict)
    # class -> {attr -> role} from @thread_confined(role, *attrs)
    confined: Dict[str, Dict[str, str]] = field(default_factory=dict)
    # class -> {attr -> lock id} from @guarded_by(lock, *attrs)
    guarded: Dict[str, Dict[str, str]] = field(default_factory=dict)
    # class -> set of @handoff attrs
    handoff: Dict[str, Set[str]] = field(default_factory=dict)
    # class -> role from @runs_on(role)
    runs_on: Dict[str, str] = field(default_factory=dict)
    # (class, method) -> pinned role from method-level @thread_confined
    pins: Dict[_Key, str] = field(default_factory=dict)
    # (class, attr) -> annotated-parameter type ("self._evb = evb"
    # where "evb: OpenrEventBase"); pruned against class_names later
    attr_param_type: Dict[Tuple[str, str], str] = field(default_factory=dict)
    # populated at finalize, read by the walkers
    executor_attrs: Set[Tuple[str, str]] = field(default_factory=set)
    evb_types: Set[str] = field(default_factory=set)


def _literal_str(node: Optional[ast.expr]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class SharedStateRule(Rule):
    id = RULE_ID
    description = (
        "an instance attribute written on one thread role and "
        "accessed on another must share a lock class (or be declared "
        "@thread_confined / @guarded_by / @handoff)"
    )

    def __init__(self) -> None:
        # reuse lock-order's collector verbatim, but store its _Model
        # under OUR scratch key so the two rules stay independent
        # (--rule shared-state must work standalone, and our typing
        # extensions must not leak into lock-order's findings)
        self._lock_collector = LockOrderRule()
        self._lock_collector.id = self.id
        #: method "Class.name" -> sorted role list; kept on the rule
        #: instance so the CLI --roles dump can read it post-run
        self.role_map: Dict[str, List[str]] = {}

    # -- collect -----------------------------------------------------

    def collect(self, sf: SourceFile, ctx: AnalysisContext) -> None:
        self._lock_collector.collect(sf, ctx)
        x: _Extra = ctx.scratch(self.id).setdefault("x", _Extra())
        for cls in sf.classes():
            x.bases.setdefault(
                cls.name,
                [b for b in (_ann_name(base) for base in cls.bases) if b],
            )
            for dec in cls.decorator_list:
                name, call = decorator_info(dec)
                leaf = name.split(".")[-1] if name else None
                if call is None or leaf is None:
                    continue
                args = [_literal_str(a) for a in call.args]
                if leaf == _ANN_RUNS_ON and args and args[0]:
                    x.runs_on[cls.name] = args[0]
                elif leaf == _ANN_THREAD_CONFINED and args and args[0]:
                    table = x.confined.setdefault(cls.name, {})
                    for a in args[1:]:
                        if a:
                            table[a] = args[0]
                elif leaf == _ANN_GUARDED_BY and args and args[0]:
                    table = x.guarded.setdefault(cls.name, {})
                    for a in args[1:]:
                        if a:
                            table[a] = args[0]
                elif leaf == _ANN_HANDOFF:
                    x.handoff.setdefault(cls.name, set()).update(
                        a for a in args if a
                    )
        for fn, cls in sf.functions():
            for dec in fn.decorator_list:
                name, call = decorator_info(dec)
                leaf = name.split(".")[-1] if name else None
                if (
                    leaf == _ANN_THREAD_CONFINED
                    and call is not None
                    and len(call.args) == 1
                ):
                    role = _literal_str(call.args[0])
                    if role:
                        x.pins[(cls, fn.name)] = role
            if cls is None:
                continue
            # "self._x = param" where the param carries a class
            # annotation: receiver typing the lock-order collector
            # (constructor calls only) cannot see
            ann: Dict[str, str] = {}
            fargs = fn.args
            for a in fargs.posonlyargs + fargs.args + fargs.kwonlyargs:
                t = _ann_name(a.annotation)
                if t is not None:
                    ann[a.arg] = t
            if not ann:
                continue
            for node in ast.walk(fn):
                target: Optional[ast.expr] = None
                value: Optional[ast.expr] = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    target, value = node.target, node.value
                if (
                    target is None
                    or value is None
                    or not isinstance(target, ast.Attribute)
                    or not isinstance(target.value, ast.Name)
                    or target.value.id != "self"
                ):
                    continue
                if isinstance(value, ast.Name) and value.id in ann:
                    x.attr_param_type.setdefault(
                        (cls, target.attr), ann[value.id]
                    )
                    continue
                # conditional construction: "self._x = Ctor(...) if
                # flag else None" (and the AnnAssign spelling) — the
                # lock-order collector only types plain Call assigns
                cands = [value]
                if isinstance(value, ast.IfExp):
                    cands = [value.body, value.orelse]
                for cand in cands:
                    if isinstance(cand, ast.Call):
                        callee = dotted_name(cand.func)
                        if callee is not None:
                            x.attr_param_type.setdefault(
                                (cls, target.attr), callee.split(".")[-1]
                            )
                            break

    # -- finalize: roles fixpoint, lock contexts, conviction ---------

    def finalize(self, ctx: AnalysisContext) -> Iterable[Finding]:
        scratch = ctx.scratch(self.id)
        model: Optional[_Model] = scratch.get("model")
        x: Optional[_Extra] = scratch.get("x")
        if model is None or x is None:
            return ()

        # merge param-derived attr types (constructor typing wins),
        # then extract executor/thread-safe attrs BEFORE the known-
        # class prune discards stdlib type names
        for k, v in x.attr_param_type.items():
            model.attr_type.setdefault(k, v)
        x.executor_attrs = {
            k
            for k, v in model.attr_type.items()
            if v == "ThreadPoolExecutor"
        }
        threadsafe_attrs = {
            k for k, v in model.attr_type.items() if v in _THREAD_SAFE_TYPES
        }
        model.attr_type = {
            k: v for k, v in model.attr_type.items() if v in model.class_names
        }
        model.returns = {
            k: v for k, v in model.returns.items() if v in model.class_names
        }
        x.evb_types = _subclass_closure(x.bases, _EVB_BASE)

        walkers: Dict[_Key, "_RaceWalk"] = {}
        for key, (fn, sf) in model.methods.items():
            w = _RaceWalk(model, key[0], fn, sf, x)
            w.run()
            walkers[key] = w

        roles = self._role_fixpoint(model, x, walkers)
        entry_held = self._held_fixpoint(model, x, roles, walkers)

        self.role_map = {
            f"{k[0] or '<module>'}.{k[1]}": sorted(v)
            for k, v in roles.items()
            if v
        }
        scratch["roles"] = self.role_map

        # -- attribute access table ---------------------------------
        table: Dict[Tuple[str, str], List[_Access]] = {}
        for key, w in walkers.items():
            cls = key[0]
            if cls is None:
                continue
            my_roles = frozenset(roles.get(key, ()))
            base_held = entry_held.get(key) or frozenset()
            in_init = key[1] == "__init__"
            for attr, write, line, held in w.accesses:
                table.setdefault((cls, attr), []).append(
                    _Access(
                        write=write,
                        line=line,
                        path=w.sf.path,
                        held=frozenset(held) | base_held,
                        roles=my_roles,
                        in_init=in_init,
                    )
                )
            for attr, write, line, role in w.pseudo:
                table.setdefault((cls, attr), []).append(
                    _Access(
                        write=write,
                        line=line,
                        path=w.sf.path,
                        held=frozenset(),
                        roles=frozenset((role,)),
                        in_init=False,
                    )
                )

        findings: List[Finding] = []
        for (cls, attr), accs in sorted(table.items()):
            if (cls, attr) in model.attr_lock:
                continue
            if (cls, attr) in threadsafe_attrs:
                continue
            if self._declared_safe(x, cls, attr):
                continue
            f = self._convict(cls, attr, accs)
            if f is not None:
                findings.append(f)
        return findings

    # -- role machinery ----------------------------------------------

    def _role_fixpoint(
        self,
        model: _Model,
        x: _Extra,
        walkers: Dict[_Key, "_RaceWalk"],
    ) -> Dict[_Key, Set[str]]:
        roles: Dict[_Key, Set[str]] = {k: set() for k in model.methods}
        frozen: Set[_Key] = set()
        for key, role in x.pins.items():
            if key in roles:
                roles[key] = {role}
                frozen.add(key)
        for cls, role in x.runs_on.items():
            for key in roles:
                if key[0] == cls and key not in frozen:
                    roles[key].add(role)
        for w in walkers.values():
            for key, role in w.entries:
                if key in roles and key not in frozen:
                    roles[key].add(role)
        calls: Dict[_Key, Set[_Key]] = {
            k: {c for c in w.called if c in model.methods}
            for k, w in walkers.items()
        }
        changed = True
        while changed:
            changed = False
            for key, callees in calls.items():
                src = roles[key]
                if not src:
                    continue
                for callee in callees:
                    if callee in frozen:
                        continue
                    dst = roles[callee]
                    before = len(dst)
                    dst |= src
                    if len(dst) != before:
                        changed = True
        return roles

    def _held_fixpoint(
        self,
        model: _Model,
        x: _Extra,
        roles: Dict[_Key, Set[str]],
        walkers: Dict[_Key, "_RaceWalk"],
    ) -> Dict[_Key, Optional[FrozenSet[str]]]:
        """Entry lock context: the intersection, over every call site
        on a role-carrying path, of locks held at the call (plus the
        caller's own entry context). A ``_locked_helper`` only ever
        invoked under ``self._mu`` inherits {Class._mu}; a thread /
        callback entry point starts with nothing held. None = not yet
        reached (top)."""
        held: Dict[_Key, Optional[FrozenSet[str]]] = {
            k: None for k in model.methods
        }
        entry_keys = {k for k, v in roles.items() if v}
        # seed: every role entry (spawn/registration target, @runs_on
        # handler method, pinned method) starts with nothing held
        seeded: Set[_Key] = set()
        for w in walkers.values():
            for key, _role in w.entries:
                if key in held:
                    seeded.add(key)
        for key in held:
            if key[0] in x.runs_on or key in x.pins:
                seeded.add(key)
        for key in seeded:
            held[key] = frozenset()
        changed = True
        while changed:
            changed = False
            for key, w in walkers.items():
                if key not in entry_keys:
                    continue
                base = held[key]
                for callee, site_held in w.call_sites:
                    if callee not in held or callee in seeded:
                        continue
                    contrib: Optional[FrozenSet[str]]
                    if base is None:
                        contrib = None
                    else:
                        contrib = frozenset(site_held) | base
                    if contrib is None:
                        continue
                    cur = held[callee]
                    nxt = contrib if cur is None else (cur & contrib)
                    if nxt != cur:
                        held[callee] = nxt
                        changed = True
        return held

    # -- conviction ---------------------------------------------------

    def _declared_safe(self, x: _Extra, cls: str, attr: str) -> bool:
        for c in _mro_chain(x.bases, cls):
            if attr in x.confined.get(c, {}):
                return True
            if attr in x.guarded.get(c, {}):
                return True
            if attr in x.handoff.get(c, ()):
                return True
        return False

    def _convict(
        self, cls: str, attr: str, accs: List[_Access]
    ) -> Optional[Finding]:
        writes = sorted(
            (a for a in accs if a.write and not a.in_init and a.roles),
            key=lambda a: (a.path, a.line),
        )
        if not writes:
            return None
        ordered = sorted(
            (a for a in accs if a.roles),
            key=lambda a: (not a.write, a.path, a.line),
        )
        for w in writes:
            for a in ordered:
                if a.in_init:
                    continue
                if w.held & a.held:
                    continue  # common lock class serializes the pair
                pair = _role_pair(w.roles, a.roles)
                if pair is None:
                    continue
                r1, r2 = pair
                kind = "written" if a.write else "read"
                same = a.line == w.line and a.path == w.path
                site = "" if same else f" ({a.path}:{a.line})"
                return Finding(
                    self.id,
                    w.path,
                    w.line,
                    0,
                    f"{cls}.{attr} written under role {r1} and {kind} "
                    f"under role {r2}{site} with no common lock class "
                    "— cross-thread race; lock both sites or declare "
                    "@thread_confined/@guarded_by/@handoff",
                )
        return None


def _role_pair(
    w_roles: FrozenSet[str], a_roles: FrozenSet[str]
) -> Optional[Tuple[str, str]]:
    """Distinct (writer role, accessor role), or None. A single-role
    pair only convicts when the roles differ; a multi-role method can
    race against itself (two threads, same code path)."""
    for r1 in sorted(w_roles):
        for r2 in sorted(a_roles):
            if r1 != r2:
                return r1, r2
    return None


def _subclass_closure(bases: Dict[str, List[str]], root: str) -> Set[str]:
    out = {root}
    changed = True
    while changed:
        changed = False
        for cls, parents in bases.items():
            if cls not in out and any(p in out for p in parents):
                out.add(cls)
                changed = True
    return out


def _mro_chain(bases: Dict[str, List[str]], cls: str) -> List[str]:
    """cls plus transitive in-tree bases (declaration-ordered DFS)."""
    seen: List[str] = []
    stack = [cls]
    while stack:
        c = stack.pop(0)
        if c in seen:
            continue
        seen.append(c)
        stack.extend(bases.get(c, ()))
    return seen


class _RaceWalk(_MethodWalk):
    """Method traversal that additionally records attribute accesses
    with their held-lock sets, every call site, and the thread-role
    entry points created by spawning / submitting / registering."""

    def __init__(
        self,
        model: _Model,
        cls: Optional[str],
        fn: ast.AST,
        sf: SourceFile,
        x: _Extra,
    ) -> None:
        super().__init__(model, cls, fn, sf)
        self.x = x
        # (attr, is_write, line, held tuple) for self.<attr> touches
        self.accesses: List[Tuple[str, bool, int, FrozenSet[str]]] = []
        # (callee key, held) for every resolvable call site
        self.call_sites: List[Tuple[_Key, FrozenSet[str]]] = []
        # (method key, role) registrations discovered here
        self.entries: List[Tuple[_Key, str]] = []
        # (attr, is_write, line, role) accesses inside lambdas handed
        # to a marshalling/registration call: they run under the
        # TARGET role, with nothing held
        self.pseudo: List[Tuple[str, bool, int, str]] = []

    # -- write-aware statement handling ------------------------------

    def _walk_stmt(self, stmt: ast.stmt, held: List[str]) -> None:
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                stmt.targets
                if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            for t in targets:
                self._record_target(t, held)
            if stmt.value is not None:
                self._scan_expr(stmt.value, held)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._record_target(t, held)
            return
        super()._walk_stmt(stmt, held)

    def _self_attr(self, node: ast.expr) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _record_target(self, t: ast.expr, held: List[str]) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._record_target(e, held)
            return
        if isinstance(t, ast.Starred):
            self._record_target(t.value, held)
            return
        if isinstance(t, ast.Attribute):
            attr = self._self_attr(t)
            if attr is not None:
                self.accesses.append(
                    (attr, True, t.lineno, frozenset(held))
                )
                return
            # obj.field = v mutates the object behind obj; if obj is a
            # self attribute, that is a write through the shared ref
            inner = self._self_attr(t.value)
            if inner is not None:
                self.accesses.append(
                    (inner, True, t.lineno, frozenset(held))
                )
                return
            self._scan_expr(t.value, held)
            return
        if isinstance(t, ast.Subscript):
            attr = self._self_attr(t.value)
            if attr is not None:
                self.accesses.append(
                    (attr, True, t.lineno, frozenset(held))
                )
            else:
                self._scan_expr(t.value, held)
            self._scan_expr(t.slice, held)
            return
        # plain Name targets are locals

    # -- expression scanning with role-aware call handling -----------

    def _scan_expr(self, expr: ast.expr, held: List[str]) -> None:
        self._scan_node(expr, held)

    def _scan_node(self, node: ast.AST, held: List[str]) -> None:
        attr = self._self_attr(node) if isinstance(node, ast.expr) else None
        if attr is not None:
            self.accesses.append((attr, False, node.lineno, frozenset(held)))
            return
        if isinstance(node, ast.Call):
            self._scan_call(node, held)
            return
        for child in ast.iter_child_nodes(node):
            self._scan_node(child, held)

    def _scan_call(self, node: ast.Call, held: List[str]) -> None:
        func = node.func
        # explicit .acquire() — mirror the parent's bookkeeping
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            lock = self._lock_id(func.value)
            if lock is not None:
                self.acquired.add(lock)
                for h in held:
                    self.nested.append(
                        (h, lock, node.lineno, f"{lock}.acquire()")
                    )
                for arg in node.args:
                    self._scan_node(arg, held)
                return
        # container mutators through a self attribute are writes
        if isinstance(func, ast.Attribute):
            recv_attr = self._self_attr(func.value)
            if recv_attr is not None and func.attr in _MUTATORS:
                self.accesses.append(
                    (recv_attr, True, node.lineno, frozenset(held))
                )
        if self._handle_registration(node, held):
            return
        key = self._callee_key(node)
        if key is not None:
            self.called.add(key)
            self.call_sites.append((key, frozenset(held)))
            for h in held:
                self.calls_while_held.append((h, key, node.lineno))
        for child in ast.iter_child_nodes(node):
            self._scan_node(child, held)

    # -- registration / spawn interception ---------------------------

    def _handle_registration(self, node: ast.Call, held: List[str]) -> bool:
        """If ``node`` hands a callable to another thread role, record
        the entry (or pseudo accesses for lambdas) and scan the
        remaining arguments normally. Returns True when handled."""
        func = node.func
        callee = dotted_name(func)
        leaf = callee.split(".")[-1] if callee else None

        role: Optional[str] = None
        cb_args: List[ast.expr] = []

        if leaf == "Thread":
            target = None
            name_lit = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
                elif kw.arg == "name":
                    name_lit = _literal_str(kw.value)
            if target is None:
                return False
            key = self._method_ref(target)
            if key is not None:
                role = self._thread_role(key, name_lit)
                self.entries.append((key, role))
            elif isinstance(target, ast.Lambda):
                role = name_lit or "thread:<lambda>"
                self._pseudo_scan(target.body, role)
            for kw in node.keywords:
                if kw.arg != "target":
                    self._scan_node(kw.value, held)
            for arg in node.args:
                self._scan_node(arg, held)
            return True

        if leaf in _EVB_CTORS:
            role = _EVB_ROLE
            cb_args = list(node.args) + [kw.value for kw in node.keywords]
        elif isinstance(func, ast.Attribute):
            if func.attr in _EVB_MARSHAL:
                role = _EVB_ROLE
                cb_args = list(node.args) + [
                    kw.value for kw in node.keywords
                ]
                # the receiver expression itself is evaluated here
                self._scan_node(func.value, held)
            elif func.attr in _LISTENER_ROLES and len(node.args) >= 1:
                role = _LISTENER_ROLES[func.attr]
                cb_args = list(node.args) + [
                    kw.value for kw in node.keywords
                ]
                self._scan_node(func.value, held)
            elif func.attr == "submit":
                recv_attr = self._self_attr(func.value)
                if (
                    recv_attr is not None
                    and self.cls is not None
                    and (self.cls, recv_attr) in self.x.executor_attrs
                ):
                    role = f"ex:{self.cls}.{recv_attr}"
                    cb_args = list(node.args) + [
                        kw.value for kw in node.keywords
                    ]
                    self._scan_node(func.value, held)
        if role is None:
            return False
        for arg in cb_args:
            self._reg_target(arg, role, held)
        return True

    def _thread_role(self, key: _Key, name_lit: Optional[str]) -> str:
        if key[1] == "run" and key[0] in self.x.evb_types:
            return _EVB_ROLE
        if name_lit:
            return name_lit
        return f"thread:{key[0] or '<module>'}.{key[1]}"

    def _method_ref(self, expr: ast.expr) -> Optional[_Key]:
        """Resolve a callable *reference* (not a call) to a method key."""
        if isinstance(expr, ast.Attribute):
            owner = self._receiver_type(expr.value)
            if owner is not None and (owner, expr.attr) in self.model.methods:
                return (owner, expr.attr)
            return None
        if isinstance(expr, ast.Name):
            for key in ((self.cls, expr.id), (None, expr.id)):
                if key in self.model.methods:
                    return key
        return None

    def _reg_target(
        self, expr: ast.expr, role: str, held: List[str]
    ) -> None:
        # functools.partial(fn, ...) — register fn, scan the rest here
        if isinstance(expr, ast.Call):
            callee = dotted_name(expr.func)
            leaf = callee.split(".")[-1] if callee else None
            if leaf == "partial" and expr.args:
                self._reg_target(expr.args[0], role, held)
                for arg in expr.args[1:]:
                    self._scan_node(arg, held)
                for kw in expr.keywords:
                    self._scan_node(kw.value, held)
                return
            self._scan_node(expr, held)
            return
        if isinstance(expr, ast.Lambda):
            self._pseudo_scan(expr.body, role)
            return
        key = self._method_ref(expr)
        if key is not None:
            if key[1] == "run" and key[0] in self.x.evb_types:
                role = _EVB_ROLE
            self.entries.append((key, role))
            return
        self._scan_node(expr, held)

    def _pseudo_scan(self, node: ast.AST, role: str) -> None:
        """Attribute accesses / calls inside a lambda body handed to
        another role: they execute there, with nothing held."""
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Attribute):
                    recv = self._self_attr(n.func.value)
                    if recv is not None and n.func.attr in _MUTATORS:
                        self.pseudo.append((recv, True, n.lineno, role))
                key = self._callee_key(n)
                if key is not None:
                    self.entries.append((key, role))
            elif isinstance(n, ast.expr):
                attr = self._self_attr(n)
                if attr is not None:
                    self.pseudo.append((attr, False, n.lineno, role))
