"""``span-discipline``: every span closes on all paths; metric names
follow the fb303 dotted convention.

``Tracer.finish`` already *counts* unclosed spans at runtime
(``telemetry.traces_unclosed_spans``), but only for traces that reach
``finish`` — a leaked span on an early-return path shows up as a
mystery counter hours later. This rule pushes the check to lint time:

- a span-opening call (``begin_span`` / ``span_active``) whose result
  is discarded can never be closed — finding;
- a span bound to a local must either be closed in the same function
  (appear as an argument to ``end_span`` / ``end_span_active``) or
  *transfer ownership* — be stored to an attribute (the debounce span
  pattern in ``decision.py``), returned, or passed into another call;
- a ``return`` between the open and the close leaks the span on that
  path, unless the close sits in a ``finally`` whose ``try`` encloses
  the return — or, in a ``@fault_boundary`` function (a degradation
  ladder rung / fault-supervisor catch site), in an ``except`` handler
  of that ``try``: the supervisor's contract is that failures re-raise
  through the handler after stamping the span, so close-in-except is a
  protected exit path there by construction, not via suppression;
- a span that transferred ownership into an *attribute* (the debounce
  span held across a window) must not be cleared (``self.x = None``)
  by a method that neither closes it nor reads it out first — that is
  exactly the overload-path leak where ``reset()`` drops an open
  ``decision.debounce`` span while a rebuild is in flight.
  ``__init__`` is exempt (declaring the slot is not a clear);
- literal metric and span names (``counter_bump`` / ``counter_set`` /
  ``observe`` / ``histogram`` / ``begin_span`` / ``span_active``) must
  match the fb303 dotted convention ``component.sub.metric`` —
  lowercase, digits, underscores, at least one dot. Dynamically built
  names (``"jax.events." + suffix``) are skipped; they are covered by
  the runtime registry, not lint;
- a ``@flight_callback`` function (an anomaly-trigger / flight-recorder
  callback registered on the wave loop) must not synchronize with the
  device in its direct body — a dump must never block a solve window,
  so raw ``jax.device_get`` / ``.block_until_ready()`` / device-scalar
  coercion forms are findings (same classifier as
  ``committed-dispatch``; host-side numpy prep stays legal).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from openr_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    decorator_info,
)
from openr_tpu.analysis.rules.hostsync import (
    CommittedDispatchRule,
    _has_decorator,
    _own_body_walk,
)


def _is_fault_boundary(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        name, _call = decorator_info(dec)
        if name and name.split(".")[-1] == "fault_boundary":
            return True
    return False

RULE_ID = "span-discipline"

_OPENERS = {"begin_span", "span_active"}
_CLOSERS = {"end_span", "end_span_active"}
_NAMED_CALLS = _OPENERS | {
    "counter_bump",
    "counter_set",
    "observe",
    "histogram",
}
_FB303_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")


def _method_leaf(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk the function without descending into nested defs."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


class SpanDisciplineRule(Rule):
    id = RULE_ID
    description = (
        "spans must close (or transfer ownership) on all paths; "
        "metric names must follow the fb303 dotted convention"
    )

    def check(self, sf: SourceFile, ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        findings.extend(self._check_names(sf))
        for fn, _cls in sf.functions():
            findings.extend(self._check_spans(sf, fn))
            findings.extend(self._check_flight_callback(sf, fn))
        assert sf.tree is not None
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_attr_clears(sf, node))
        return findings

    # -- flight-callback host-sync ban --------------------------------

    def _check_flight_callback(
        self, sf: SourceFile, fn: ast.AST
    ) -> Iterable[Finding]:
        """An anomaly-trigger callback runs on the wave loop between
        solves; any device sync in it stalls every tenant in the wave.
        Same classifier as ``committed-dispatch`` (raw device_get /
        block_until_ready / device-scalar coercion; host numpy ok)."""
        if not _has_decorator(fn, "flight_callback"):
            return []
        classifier = CommittedDispatchRule()
        findings: List[Finding] = []
        for node in _own_body_walk(fn):
            hit = classifier._classify(node)
            if hit is not None:
                findings.append(
                    Finding(
                        self.id, sf.path, node.lineno, node.col_offset,
                        f"{hit} inside @flight_callback '{fn.name}' — "
                        "an anomaly-trigger callback must never block "
                        "a solve window (note() the evidence; the "
                        "flight recorder defers the dump to window "
                        "retirement)",
                    )
                )
        return findings

    # -- metric / span naming ----------------------------------------

    def _check_names(self, sf: SourceFile) -> Iterable[Finding]:
        assert sf.tree is not None
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = _method_leaf(node)
            if leaf not in _NAMED_CALLS or not node.args:
                continue
            arg = node.args[0]
            if not (
                isinstance(arg, ast.Constant) and isinstance(arg.value, str)
            ):
                continue  # dynamically built names: runtime's problem
            if not _FB303_RE.match(arg.value):
                yield Finding(
                    self.id,
                    sf.path,
                    node.lineno,
                    node.col_offset,
                    f"{leaf}() name '{arg.value}' violates the fb303 "
                    "dotted convention (lowercase "
                    "'component.sub.metric', at least one dot)",
                )

    # -- span open/close pairing -------------------------------------

    def _check_spans(self, sf: SourceFile, fn: ast.AST) -> Iterable[Finding]:
        findings: List[Finding] = []
        # var -> line of the span-opening assignment
        opens: Dict[str, int] = {}
        discarded: List[Tuple[int, int, str]] = []
        for node in _own_nodes(fn):
            if isinstance(node, ast.Expr) and self._opener_in(node.value):
                leaf = self._opener_in(node.value)
                discarded.append((node.lineno, node.col_offset, leaf))
            elif isinstance(node, ast.Assign):
                leaf = self._opener_in(node.value)
                if leaf and len(node.targets) == 1 and isinstance(
                    node.targets[0], ast.Name
                ):
                    opens[node.targets[0].id] = node.lineno

        for line, col, leaf in discarded:
            findings.append(
                Finding(
                    self.id, sf.path, line, col,
                    f"{leaf}() result discarded — the span can never "
                    "be closed (bind it and end_span it, or drop the "
                    "span entirely)",
                )
            )
        if not opens:
            return findings

        closed_at: Dict[str, int] = {}
        escaped: Set[str] = set()
        returns: List[int] = []
        for node in _own_nodes(fn):
            if isinstance(node, ast.Call):
                leaf = _method_leaf(node)
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name) and sub.id in opens:
                            if leaf in _CLOSERS:
                                closed_at[sub.id] = max(
                                    closed_at.get(sub.id, 0), node.lineno
                                )
                            else:
                                escaped.add(sub.id)
            elif isinstance(node, ast.Return):
                returns.append(node.lineno)
                if node.value is not None:
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Name) and sub.id in opens:
                            escaped.add(sub.id)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                value = node.value
                for tgt in targets:
                    if isinstance(
                        tgt, (ast.Attribute, ast.Subscript)
                    ) and isinstance(value, ast.Name) and value.id in opens:
                        escaped.add(value.id)

        protected = self._protected_ranges(fn)
        for var, open_line in sorted(opens.items(), key=lambda kv: kv[1]):
            close = closed_at.get(var)
            if close is None:
                if var not in escaped:
                    findings.append(
                        Finding(
                            self.id, sf.path, open_line, 0,
                            f"span '{var}' opened here is never closed "
                            "and never transfers ownership (no "
                            "end_span*, attribute store, return, or "
                            "call argument)",
                        )
                    )
                continue
            for rline in returns:
                if open_line < rline < close and not any(
                    t0 <= rline <= t1 and f0 <= close <= f1
                    for (t0, t1, f0, f1) in protected
                ):
                    findings.append(
                        Finding(
                            self.id, sf.path, rline, 0,
                            f"return leaks span '{var}' (opened line "
                            f"{open_line}, closed line {close}) — close "
                            "before returning or move the close into a "
                            "finally",
                        )
                    )
                    break
        return findings

    # -- span-attribute clears ----------------------------------------

    def _check_attr_clears(
        self, sf: SourceFile, cls: ast.ClassDef
    ) -> Iterable[Finding]:
        """A ``self.<attr> = None`` that drops a span-holding attribute
        without first closing it (end_span*) or reading it out (into a
        local / call / return) leaks the open span. This is the
        overload-reset leak: a method that wipes state while a span is
        still riding the attribute."""
        methods = [
            n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        # pass 1: which attributes ever hold a span? Either assigned a
        # span-opening call directly, or assigned a local that was bound
        # to one in the same method.
        span_attrs: Set[str] = set()
        for fn in methods:
            opens: Set[str] = set()
            for node in _own_nodes(fn):
                if not isinstance(node, ast.Assign):
                    continue
                if self._opener_in(node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            opens.add(tgt.id)
                        elif self._is_self_attr(tgt):
                            span_attrs.add(tgt.attr)
                elif isinstance(node.value, ast.Name) and node.value.id in opens:
                    for tgt in node.targets:
                        if self._is_self_attr(tgt):
                            span_attrs.add(tgt.attr)
        if not span_attrs:
            return []
        # pass 2: find clears that neither close nor read out first
        findings: List[Finding] = []
        for fn in methods:
            if fn.name == "__init__":
                continue  # declaring the slot is not a clear
            clears: List[Tuple[str, int, int]] = []
            reads: Dict[str, List[int]] = {}
            for node in _own_nodes(fn):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if (
                            self._is_self_attr(tgt)
                            and tgt.attr in span_attrs
                            and isinstance(node.value, ast.Constant)
                            and node.value.value is None
                        ):
                            clears.append(
                                (tgt.attr, node.lineno, node.col_offset)
                            )
                if isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, ast.Load
                ):
                    if (
                        self._is_self_attr(node)
                        and node.attr in span_attrs
                    ):
                        reads.setdefault(node.attr, []).append(node.lineno)
            for attr, line, col in clears:
                if any(r <= line for r in reads.get(attr, [])):
                    continue  # read out (or closed via a read) first
                findings.append(
                    Finding(
                        self.id, sf.path, line, col,
                        f"clearing span attribute 'self.{attr}' without "
                        "closing it or reading it out first leaks the "
                        "open span on this path (end_span it, or bind "
                        "it to a local before the clear)",
                    )
                )
        return findings

    @staticmethod
    def _is_self_attr(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        )

    def _opener_in(self, expr: ast.expr) -> Optional[str]:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                leaf = _method_leaf(sub)
                if leaf in _OPENERS:
                    return leaf
        return None

    def _protected_ranges(
        self, fn: ast.AST
    ) -> List[Tuple[int, int, int, int]]:
        """(try_start, try_end, close_start, close_end) line ranges:
        a return inside [try_start, try_end] is covered by a close
        inside [close_start, close_end]. The close range is a
        ``finally`` for any function; in a ``@fault_boundary``
        function an ``except`` handler body also counts — the
        supervisor's catch-and-re-raise shape closes the span on the
        failure path there by contract."""
        fault_boundary = _is_fault_boundary(fn)
        out: List[Tuple[int, int, int, int]] = []
        for node in _own_nodes(fn):
            if not isinstance(node, ast.Try):
                continue
            t0 = node.body[0].lineno
            t1 = max(
                getattr(n, "end_lineno", n.lineno)
                for n in node.body + node.handlers + node.orelse
            )
            if node.finalbody:
                f0 = node.finalbody[0].lineno
                f1 = max(
                    getattr(n, "end_lineno", n.lineno)
                    for n in node.finalbody
                )
                out.append((t0, t1, f0, f1))
            if fault_boundary:
                for handler in node.handlers:
                    h0 = handler.body[0].lineno
                    h1 = max(
                        getattr(n, "end_lineno", n.lineno)
                        for n in handler.body
                    )
                    out.append((t0, t1, h0, h1))
        return out
