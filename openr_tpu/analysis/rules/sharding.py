"""``sharding-spec``: resident buffers only flow into placement-aware
dispatches.

The resharding-free contract (issue 7): every resident device buffer
gets an explicit ``NamedSharding`` at build time, and every jitted
dispatch that consumes one must DECLARE its placement — via
``in_shardings``/``out_shardings`` on the jit, or by being a
``shard_map`` dispatch (whose in/out_specs are the declaration), or by
riding ``replicated_jit`` (which commits both sides replicated). A
bare ``jax.jit`` consuming a resident leaves placement to XLA's
sharding propagation: it usually guesses right today, and then a
refactor moves one operand and every churn dispatch silently pays a
reshard or replication copy — the storm ``ops.reshard_events`` exists
to catch at runtime. This rule catches it at review time.

Detection mirrors ``donation-hazard``'s conventions: resident names
come from ``@resident_buffers`` registrations plus the ``_dr`` /
``_*_dev`` spellings, with alias tainting through locals. A jitted
callable "declares shardings" when:

- its decorator call carries ``in_shardings`` or ``out_shardings``;
- its body dispatches through ``shard_map`` (specs are per-operand
  there);
- it is a module-level ``name = jax.jit(fn, in_shardings=..., ...)``
  binding with either kwarg.

Only call sites inside ``openr_tpu/ops/`` and ``openr_tpu/decision/``
are checked — that is where the resident churn path lives. Single-chip
dispatch sites (no mesh, nothing to spec) carry audited suppressions.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from openr_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    call_kwarg,
    decorator_info,
    dotted_name,
    literal_or_none,
    unwrap_aot_call,
)
from openr_tpu.analysis.rules.donation import _is_resident_name

RULE_ID = "sharding-spec"

#: path fragments of the checked surface (the resident churn path)
_CHECKED_DIRS = ("openr_tpu/ops/", "openr_tpu/decision/")

_SHARDING_KWARGS = ("in_shardings", "out_shardings")


def _declares_shardings(call: Optional[ast.Call]) -> bool:
    if call is None:
        return False
    return any(call_kwarg(call, kw) is not None for kw in _SHARDING_KWARGS)


def _body_uses_shard_map(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            if callee is not None and callee.split(".")[-1] == "shard_map":
                return True
    return False


class ShardingSpecRule(Rule):
    id = RULE_ID
    description = (
        "jitted dispatches consuming resident buffers in ops/ and "
        "decision/ must declare in_shardings/out_shardings (or be "
        "shard_map / replicated_jit dispatches)"
    )

    # -- collect: jitted callables and whether they declare ----------

    def collect(self, sf: SourceFile, ctx: AnalysisContext) -> None:
        store = ctx.scratch(self.id)
        jitted: Dict[str, bool] = store.setdefault("jitted", {})
        resident: Set[str] = store.setdefault("resident", set())

        for cls in sf.classes():
            for dec in cls.decorator_list:
                name, call = decorator_info(dec)
                if name and name.split(".")[-1] == "resident_buffers" and call:
                    for arg in call.args:
                        val = literal_or_none(arg)
                        if isinstance(val, str):
                            resident.add(val)

        for fn, _cls in sf.functions():
            for dec in fn.decorator_list:
                name, call = decorator_info(dec)
                if name is None or name.split(".")[-1] != "jit":
                    continue
                jitted[fn.name] = (
                    _declares_shardings(call) or _body_uses_shard_map(fn)
                )

        # module-level `name = jax.jit(fn, ...)` bindings
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            if not isinstance(val, ast.Call):
                continue
            callee = dotted_name(val.func)
            if callee is None or callee.split(".")[-1] != "jit":
                continue
            declares = _declares_shardings(val)
            if not declares and val.args:
                inner = dotted_name(val.args[0])
                if inner is not None:
                    # jit(fn) over a shard_map-dispatching body counts
                    for fn, _cls in sf.functions():
                        if fn.name == inner.split(".")[-1]:
                            declares = _body_uses_shard_map(fn)
                            break
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    jitted[tgt.id] = declares

    # -- check: resident args into non-declaring dispatches ----------

    def check(self, sf: SourceFile, ctx: AnalysisContext) -> Iterable[Finding]:
        path = sf.path.replace("\\", "/")
        if not any(frag in path for frag in _CHECKED_DIRS):
            return []
        store = ctx.scratch(self.id)
        jitted: Dict[str, bool] = store.get("jitted", {})
        resident: Set[str] = store.get("resident", set())
        findings: List[Finding] = []

        for fn, _cls in sf.functions():
            tainted: Dict[str, str] = {}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Attribute
                ):
                    if _is_resident_name(node.value.attr, resident):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                tainted[tgt.id] = node.value.attr

            def resident_in(expr: ast.expr) -> Optional[str]:
                for sub in ast.walk(expr):
                    if isinstance(sub, ast.Attribute) and _is_resident_name(
                        sub.attr, resident
                    ):
                        return sub.attr
                    if isinstance(sub, ast.Name) and sub.id in tainted:
                        return f"{sub.id} (= self.{tainted[sub.id]})"
                return None

            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted_name(node.func)
                if callee is None:
                    continue
                call_args = list(node.args) + [
                    kw.value for kw in node.keywords
                ]
                aot = unwrap_aot_call(node)
                if aot is not None:
                    # dispatch behind the AOT executable cache: the
                    # wrapped fn + its dyn-arg tuple are the real site
                    callee, call_args = aot
                leaf = callee.split(".")[-1]
                declares = jitted.get(leaf)
                if declares is not False:
                    # unknown callable or a declaring dispatch
                    continue
                for arg in call_args:
                    hit = resident_in(arg)
                    if hit is not None:
                        findings.append(
                            Finding(
                                self.id, sf.path, node.lineno,
                                node.col_offset,
                                f"resident buffer {hit} flows into "
                                f"{leaf}, a jitted dispatch with no "
                                "in_shardings/out_shardings — XLA "
                                "chooses the placement, and a reshard "
                                "copy lands on the churn path the day "
                                "propagation guesses differently",
                            )
                        )
                        break
        return findings
