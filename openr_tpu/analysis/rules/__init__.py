"""The eleven invariant checkers. Each module exports its Rule classes;
``ALL_RULES`` is the canonical registry consumed by
``core.run_analysis`` and the CLI."""

from openr_tpu.analysis.rules.donation import DonationHazardRule
from openr_tpu.analysis.rules.hostsync import (
    CommittedDispatchRule,
    HostBranchInChainRule,
    HostSyncInWindowRule,
)
from openr_tpu.analysis.rules.lockorder import LockOrderRule
from openr_tpu.analysis.rules.mirror_coverage import MirrorCoverageRule
from openr_tpu.analysis.rules.races import SharedStateRule
from openr_tpu.analysis.rules.retrace import RetraceRiskRule
from openr_tpu.analysis.rules.sharding import ShardingSpecRule
from openr_tpu.analysis.rules.spans import SpanDisciplineRule
from openr_tpu.analysis.rules.vmem import VmemBudgetRule

ALL_RULES = (
    DonationHazardRule,
    HostSyncInWindowRule,
    CommittedDispatchRule,
    HostBranchInChainRule,
    LockOrderRule,
    SharedStateRule,
    SpanDisciplineRule,
    RetraceRiskRule,
    ShardingSpecRule,
    MirrorCoverageRule,
    VmemBudgetRule,
)

__all__ = [
    "ALL_RULES",
    "CommittedDispatchRule",
    "DonationHazardRule",
    "HostBranchInChainRule",
    "HostSyncInWindowRule",
    "LockOrderRule",
    "MirrorCoverageRule",
    "SharedStateRule",
    "SpanDisciplineRule",
    "RetraceRiskRule",
    "ShardingSpecRule",
    "VmemBudgetRule",
]
