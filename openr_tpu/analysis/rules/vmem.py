"""``vmem-budget``: every pallas kernel module declares its VMEM
footprint, and the declaration tracks the module's tile constants.

A Mosaic kernel that silently outgrows scoped VMEM fails on hardware
only — CPU interpret mode (what tier-1 runs) has no 16 MB ceiling, so
the first signal is a compile error on a TPU pod at deploy time. The
repo convention is that each module calling ``pallas_call`` exposes a
module-level ``vmem_bytes(...)`` function computing the per-grid-step
resident footprint from the SAME tile constants / tile planners the
``BlockSpec``s use, so benches and smoke tools can assert the budget
without lowering. This rule pins the convention statically:

- a module that calls ``pallas_call`` but defines no module-level
  ``vmem_bytes`` is a finding (undeclared budget);
- ``vmem_bytes`` (including any module-level helpers it calls,
  transitively) must reference every module-level ``TILE_*`` constant
  — a tile dim the budget does not account for means the declared
  bound and the actual kernel footprint have diverged;
- if the module declares no ``TILE_*`` constants (tile sizes come from
  a planner), the ``vmem_bytes`` closure must still reference at least
  one module-level ALL_CAPS constant (the budget cap the planner
  enforces, e.g. ``_TEMP_BUDGET``) — otherwise the declaration is
  detached from anything the kernel actually obeys.

Fixture-only modules and non-pallas code never trigger: the rule keys
strictly on the presence of a ``pallas_call`` callsite.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set

from openr_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    dotted_name,
)

RULE_ID = "vmem-budget"

_CONST_RE = re.compile(r"^_?[A-Z][A-Z0-9_]*$")
_TILE_RE = re.compile(r"^TILE_[A-Z0-9_]+$")


def _module_constants(tree: ast.Module) -> Set[str]:
    """Top-level ALL_CAPS assignment targets (leading underscore ok)."""
    out: Set[str] = set()
    for node in tree.body:
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and _CONST_RE.match(t.id):
                out.add(t.id)
    return out


def _module_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    return {
        node.name: node
        for node in tree.body
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _referenced_names(fn: ast.AST) -> Set[str]:
    return {
        n.id for n in ast.walk(fn) if isinstance(n, ast.Name)
    }


def _closure_names(
    fn: ast.AST, functions: Dict[str, ast.AST]
) -> Set[str]:
    """Names referenced by ``fn`` plus, transitively, by every
    module-level function it references (the tile-planner hop:
    ``vmem_bytes`` -> ``_pick_tiles`` -> ``_TEMP_BUDGET``)."""
    seen_fns: Set[str] = set()
    names: Set[str] = set()
    work = [fn]
    while work:
        cur = work.pop()
        for name in _referenced_names(cur):
            names.add(name)
            if name in functions and name not in seen_fns:
                seen_fns.add(name)
                work.append(functions[name])
    return names


def _first_pallas_call(tree: ast.Module) -> Optional[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            if callee and callee.split(".")[-1] == "pallas_call":
                return node
    return None


class VmemBudgetRule(Rule):
    id = RULE_ID
    description = (
        "pallas kernel modules declare vmem_bytes and the declaration "
        "references the tile constants that bound the VMEM temporary"
    )

    def check(
        self, sf: SourceFile, ctx: AnalysisContext
    ) -> Iterable[Finding]:
        if sf.tree is None:
            return
        site = _first_pallas_call(sf.tree)
        if site is None:
            return
        functions = _module_functions(sf.tree)
        budget = functions.get("vmem_bytes")
        if budget is None:
            yield Finding(
                rule=self.id,
                path=sf.path,
                line=site.lineno,
                col=site.col_offset,
                message=(
                    "module calls pallas_call but declares no module-"
                    "level vmem_bytes budget function"
                ),
            )
            return
        consts = _module_constants(sf.tree)
        tiles = sorted(c for c in consts if _TILE_RE.match(c))
        closure = _closure_names(budget, functions)
        missing = [t for t in tiles if t not in closure]
        for tile in missing:
            yield Finding(
                rule=self.id,
                path=sf.path,
                line=budget.lineno,
                col=budget.col_offset,
                message=(
                    f"vmem_bytes does not account for tile constant "
                    f"{tile}: the declared budget no longer bounds "
                    f"the kernel's VMEM temporary"
                ),
            )
        if not tiles and not (closure & consts):
            yield Finding(
                rule=self.id,
                path=sf.path,
                line=budget.lineno,
                col=budget.col_offset,
                message=(
                    "vmem_bytes references no module-level tile or "
                    "budget constant; the declaration is detached "
                    "from what the kernel obeys"
                ),
            )
