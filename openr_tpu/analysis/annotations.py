"""Runtime-inert annotation API the static checkers key on.

The lint rules need ground truth that types alone cannot carry: which
attributes are device-resident buffers, which functions run inside a
solve window, which cold-rebuild paths must drain the pending delta
first, and which plain-Python wrappers donate specific parameters into
a jitted dispatch. These decorators record exactly that — as function /
class attributes at runtime (free after import; nothing on the hot
path reads them) and as names the AST pass recognizes syntactically.

The decorators MUST stay dependency-free (no jax, no numpy): annotated
modules import this at module load, including under ``make
lint-analysis`` which never touches an accelerator runtime.
"""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)
C = TypeVar("C", bound=type)

#: attribute names the markers are stored under (shared with the AST
#: rules so both sides agree on one spelling)
SOLVE_WINDOW_ATTR = "__openr_solve_window__"
COMMITTED_DISPATCH_ATTR = "__openr_committed_dispatch__"
RESIDENT_ATTR = "__openr_resident_buffers__"
REQUIRES_DRAIN_ATTR = "__openr_requires_drain__"
DONATES_ATTR = "__openr_donates__"
FAULT_BOUNDARY_ATTR = "__openr_fault_boundary__"
MIRROR_ATTR = "__openr_host_mirrors__"
FLIGHT_CALLBACK_ATTR = "__openr_flight_callback__"
THREAD_CONFINED_ATTR = "__openr_thread_confined__"
GUARDED_BY_ATTR = "__openr_guarded_by__"
HANDOFF_ATTR = "__openr_handoff__"
RUNS_ON_ATTR = "__openr_runs_on__"


def solve_window(fn: F) -> F:
    """Mark a function as solve-window code: it runs between a churn
    dispatch and its commit, where any host synchronization
    (``np.asarray`` on a device array, ``jax.device_get``,
    ``.block_until_ready()``, ``float()`` on an Array) serializes the
    device pipeline. The ``host-sync-in-window`` rule flags those call
    forms in the function's direct body."""
    try:
        setattr(fn, SOLVE_WINDOW_ATTR, True)
    except AttributeError:
        # jit-wrapped callables may reject attributes; the static
        # checker reads the decorator syntactically either way
        pass
    return fn


def committed_dispatch(fn: F) -> F:
    """Mark a function as committed-dispatch code: it lives on the
    event path between SUBMIT (program launches) and REAP (async
    readback drain), where the host may touch the device only through
    the sanctioned ``ops.dispatch_accounting`` helpers
    (``count_dispatch`` / ``kick_async`` / ``reap_read``). The
    ``committed-dispatch`` rule flags raw ``jax.device_get`` /
    ``.block_until_ready()`` / device-scalar coercion forms in the
    function's direct body — each one is an unaccounted host round
    trip that serializes the event window."""
    try:
        setattr(fn, COMMITTED_DISPATCH_ATTR, True)
    except AttributeError:
        pass
    return fn


def resident_buffers(*attr_names: str) -> Callable[[C], C]:
    """Class decorator registering device-RESIDENT buffer attributes
    (``_packed_dev``-style state that later dispatches re-read). The
    ``donation-hazard`` rule flags any of these flowing into a donating
    dispatch or being read after donation."""

    def deco(cls: C) -> C:
        merged = tuple(getattr(cls, RESIDENT_ATTR, ())) + attr_names
        setattr(cls, RESIDENT_ATTR, merged)
        return cls

    return deco


def mirrored_by(**mirrors: str) -> Callable[[C], C]:
    """Class decorator declaring, per ``@resident_buffers`` name, the
    settle-on-success host mirror (an attribute name) or the rebuild
    recipe (a prose description) that makes the buffer healable after
    silent corruption or device loss. The ``mirror-coverage`` rule
    requires every registered resident buffer to appear here or carry
    an in-source audited suppression — a resident with neither is
    unhealable state waiting to strand a quarantined engine."""

    def deco(cls: C) -> C:
        merged = dict(getattr(cls, MIRROR_ATTR, {}))
        merged.update(mirrors)
        setattr(cls, MIRROR_ATTR, merged)
        return cls

    return deco


def requires_drain(drain_call: str) -> Callable[[F], F]:
    """Mark a method that replaces resident state wholesale (a cold
    rebuild): it must invoke ``drain_call`` (e.g. ``flush``) before any
    write to a resident buffer, so a caller-held ``PendingDelta``
    resolves instead of dangling over freed device state. Checked by
    ``donation-hazard``."""

    def deco(fn: F) -> F:
        try:
            setattr(fn, REQUIRES_DRAIN_ATTR, drain_call)
        except AttributeError:
            pass
        return fn

    return deco


def fault_boundary(fn: F) -> F:
    """Mark a function as a degradation-ladder rung or fault-supervisor
    catch site: it may be re-entered after a mid-flight failure, so the
    buffers it touches must still be valid on the SECOND attempt. The
    ``donation-hazard`` rule therefore flags *any* donation inside a
    fault boundary (a deeper rung would re-dispatch against an already
    invalidated buffer), and the ``span-discipline`` rule accepts its
    close-in-except + re-raise shape as a protected exit path."""
    try:
        setattr(fn, FAULT_BOUNDARY_ATTR, True)
    except AttributeError:
        pass
    return fn


def flight_callback(fn: F) -> F:
    """Mark a function as an anomaly-trigger / flight-recorder callback
    that runs on the wave loop or another dispatch-adjacent thread. A
    post-mortem dump is file I/O plus a full counter snapshot, so a
    callback body must never synchronize with the device — the
    ``span-discipline`` rule flags raw host-sync forms
    (``jax.device_get``, ``.block_until_ready()``, device-scalar
    coercion) in its direct body. Dump deferral lives in
    ``telemetry.flight._fire``; this marker keeps callback authors
    honest about everything else."""
    try:
        setattr(fn, FLIGHT_CALLBACK_ATTR, True)
    except AttributeError:
        pass
    return fn


def thread_confined(role: str, *attr_names: str):
    """Declare thread confinement for the ``shared-state`` rule.

    Two forms:

    - **class decorator** ``@thread_confined("evb:Decision", "_attr",
      ...)`` — the named instance attributes are only ever touched
      while the object is driven by the given role (the role names
      come from ``python -m openr_tpu.analysis --roles``). The rule
      exempts those attributes from cross-role conviction; the runtime
      sanitizer (:mod:`openr_tpu.analysis.racedep`) can still convict
      the claim if it is a lie.
    - **method decorator** ``@thread_confined("wave-loop")`` (no attr
      names) — pins the method's may-run-on role set to exactly this
      role, overriding inference. For callbacks reached through
      registries the static pass cannot see.
    """

    def deco(obj):
        if isinstance(obj, type) or attr_names:
            merged = dict(getattr(obj, THREAD_CONFINED_ATTR, {}))
            for a in attr_names:
                merged[a] = role
            try:
                setattr(obj, THREAD_CONFINED_ATTR, merged)
            except AttributeError:
                pass
        else:
            try:
                setattr(obj, THREAD_CONFINED_ATTR, {"__method__": role})
            except AttributeError:
                pass
        return obj

    return deco


def guarded_by(lock_id: str, *attr_names: str) -> Callable[[C], C]:
    """Class decorator declaring that the named instance attributes are
    always accessed under the given lock class (``"Class._lock"`` —
    identity shared with the ``lock-order`` rule). The ``shared-state``
    rule exempts the attributes AND trusts the declaration enough to
    skip held-lock reconstruction at sites its with-stack tracking
    cannot see (callbacks invoked under a caller's lock). Audited by
    the runtime sanitizer, which observes the locks actually held."""

    def deco(cls: C) -> C:
        merged = dict(getattr(cls, GUARDED_BY_ATTR, {}))
        for a in attr_names:
            merged[a] = lock_id
        setattr(cls, GUARDED_BY_ATTR, merged)
        return cls

    return deco


def handoff(*attr_names: str) -> Callable[[C], C]:
    """Class decorator declaring publish-once-then-immutable handoff
    attributes: written by one role (usually ``__init__`` or a single
    setup method) before any other role can observe the object, never
    mutated after publication. The classic safe patterns — config
    snapshots, frozen route products swapped in whole — are handoffs,
    not races; this names them so the ``shared-state`` rule does not
    cry wolf."""

    def deco(cls: C) -> C:
        merged = tuple(getattr(cls, HANDOFF_ATTR, ())) + attr_names
        setattr(cls, HANDOFF_ATTR, merged)
        return cls

    return deco


def runs_on(role: str) -> Callable[[C], C]:
    """Class decorator pinning EVERY method of the class to one thread
    role. For handler classes reached through dynamic dispatch the
    static pass cannot resolve (the ctrl server's ``getattr`` method
    lookup runs each handler on a per-connection socketserver thread).
    Methods of a ``@runs_on("ctrl")`` class seed the role fixpoint with
    that role, so attribute accesses they make — and calls they fan out
    into the rest of the tree — carry ctrl-thread provenance."""

    def deco(cls: C) -> C:
        setattr(cls, RUNS_ON_ATTR, role)
        return cls

    return deco


def donates(*param_names: str) -> Callable[[F], F]:
    """Mark a plain-Python wrapper whose named parameters are forwarded
    into a ``donate_argnums`` position of a jitted dispatch (the array
    is invalid after the call). Lets the ``donation-hazard`` rule check
    cross-module call sites without whole-program type inference."""

    def deco(fn: F) -> F:
        try:
            setattr(fn, DONATES_ATTR, tuple(param_names))
        except AttributeError:
            pass
        return fn

    return deco
