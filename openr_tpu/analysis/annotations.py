"""Runtime-inert annotation API the static checkers key on.

The lint rules need ground truth that types alone cannot carry: which
attributes are device-resident buffers, which functions run inside a
solve window, which cold-rebuild paths must drain the pending delta
first, and which plain-Python wrappers donate specific parameters into
a jitted dispatch. These decorators record exactly that — as function /
class attributes at runtime (free after import; nothing on the hot
path reads them) and as names the AST pass recognizes syntactically.

The decorators MUST stay dependency-free (no jax, no numpy): annotated
modules import this at module load, including under ``make
lint-analysis`` which never touches an accelerator runtime.
"""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)
C = TypeVar("C", bound=type)

#: attribute names the markers are stored under (shared with the AST
#: rules so both sides agree on one spelling)
SOLVE_WINDOW_ATTR = "__openr_solve_window__"
COMMITTED_DISPATCH_ATTR = "__openr_committed_dispatch__"
RESIDENT_ATTR = "__openr_resident_buffers__"
REQUIRES_DRAIN_ATTR = "__openr_requires_drain__"
DONATES_ATTR = "__openr_donates__"
FAULT_BOUNDARY_ATTR = "__openr_fault_boundary__"
MIRROR_ATTR = "__openr_host_mirrors__"
FLIGHT_CALLBACK_ATTR = "__openr_flight_callback__"


def solve_window(fn: F) -> F:
    """Mark a function as solve-window code: it runs between a churn
    dispatch and its commit, where any host synchronization
    (``np.asarray`` on a device array, ``jax.device_get``,
    ``.block_until_ready()``, ``float()`` on an Array) serializes the
    device pipeline. The ``host-sync-in-window`` rule flags those call
    forms in the function's direct body."""
    try:
        setattr(fn, SOLVE_WINDOW_ATTR, True)
    except AttributeError:
        # jit-wrapped callables may reject attributes; the static
        # checker reads the decorator syntactically either way
        pass
    return fn


def committed_dispatch(fn: F) -> F:
    """Mark a function as committed-dispatch code: it lives on the
    event path between SUBMIT (program launches) and REAP (async
    readback drain), where the host may touch the device only through
    the sanctioned ``ops.dispatch_accounting`` helpers
    (``count_dispatch`` / ``kick_async`` / ``reap_read``). The
    ``committed-dispatch`` rule flags raw ``jax.device_get`` /
    ``.block_until_ready()`` / device-scalar coercion forms in the
    function's direct body — each one is an unaccounted host round
    trip that serializes the event window."""
    try:
        setattr(fn, COMMITTED_DISPATCH_ATTR, True)
    except AttributeError:
        pass
    return fn


def resident_buffers(*attr_names: str) -> Callable[[C], C]:
    """Class decorator registering device-RESIDENT buffer attributes
    (``_packed_dev``-style state that later dispatches re-read). The
    ``donation-hazard`` rule flags any of these flowing into a donating
    dispatch or being read after donation."""

    def deco(cls: C) -> C:
        merged = tuple(getattr(cls, RESIDENT_ATTR, ())) + attr_names
        setattr(cls, RESIDENT_ATTR, merged)
        return cls

    return deco


def mirrored_by(**mirrors: str) -> Callable[[C], C]:
    """Class decorator declaring, per ``@resident_buffers`` name, the
    settle-on-success host mirror (an attribute name) or the rebuild
    recipe (a prose description) that makes the buffer healable after
    silent corruption or device loss. The ``mirror-coverage`` rule
    requires every registered resident buffer to appear here or carry
    an in-source audited suppression — a resident with neither is
    unhealable state waiting to strand a quarantined engine."""

    def deco(cls: C) -> C:
        merged = dict(getattr(cls, MIRROR_ATTR, {}))
        merged.update(mirrors)
        setattr(cls, MIRROR_ATTR, merged)
        return cls

    return deco


def requires_drain(drain_call: str) -> Callable[[F], F]:
    """Mark a method that replaces resident state wholesale (a cold
    rebuild): it must invoke ``drain_call`` (e.g. ``flush``) before any
    write to a resident buffer, so a caller-held ``PendingDelta``
    resolves instead of dangling over freed device state. Checked by
    ``donation-hazard``."""

    def deco(fn: F) -> F:
        try:
            setattr(fn, REQUIRES_DRAIN_ATTR, drain_call)
        except AttributeError:
            pass
        return fn

    return deco


def fault_boundary(fn: F) -> F:
    """Mark a function as a degradation-ladder rung or fault-supervisor
    catch site: it may be re-entered after a mid-flight failure, so the
    buffers it touches must still be valid on the SECOND attempt. The
    ``donation-hazard`` rule therefore flags *any* donation inside a
    fault boundary (a deeper rung would re-dispatch against an already
    invalidated buffer), and the ``span-discipline`` rule accepts its
    close-in-except + re-raise shape as a protected exit path."""
    try:
        setattr(fn, FAULT_BOUNDARY_ATTR, True)
    except AttributeError:
        pass
    return fn


def flight_callback(fn: F) -> F:
    """Mark a function as an anomaly-trigger / flight-recorder callback
    that runs on the wave loop or another dispatch-adjacent thread. A
    post-mortem dump is file I/O plus a full counter snapshot, so a
    callback body must never synchronize with the device — the
    ``span-discipline`` rule flags raw host-sync forms
    (``jax.device_get``, ``.block_until_ready()``, device-scalar
    coercion) in its direct body. Dump deferral lives in
    ``telemetry.flight._fire``; this marker keeps callback authors
    honest about everything else."""
    try:
        setattr(fn, FLIGHT_CALLBACK_ATTR, True)
    except AttributeError:
        pass
    return fn


def donates(*param_names: str) -> Callable[[F], F]:
    """Mark a plain-Python wrapper whose named parameters are forwarded
    into a ``donate_argnums`` position of a jitted dispatch (the array
    is invalid after the call). Lets the ``donation-hazard`` rule check
    cross-module call sites without whole-program type inference."""

    def deco(fn: F) -> F:
        try:
            setattr(fn, DONATES_ATTR, tuple(param_names))
        except AttributeError:
            pass
        return fn

    return deco
