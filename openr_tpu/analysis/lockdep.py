"""Runtime lock-order tracker (lockdep-style), the dynamic companion to
the static ``lock-order`` rule.

The static graph over-approximates: it cannot see locks handed through
queues or callbacks registered at runtime. This shim closes that gap
the way the kernel's lockdep does — it learns the *order* in which lock
classes are taken and flags an inversion the first time the reversed
order is observed on ANY thread, without needing the actual deadlock to
strike:

    thread 1: with a: with b: ...      # learns edge A -> B
    thread 2: with b: with a: ...      # B -> A closes a cycle -> flag

Usage (tests; production code never imports this on the hot path)::

    dep = LockDepTracker()
    a = TrackedLock("kvstore.store", tracker=dep)
    b = TrackedLock("telemetry.registry", tracker=dep)
    with a, b: ...
    with b, a: ...          # -> LockOrderViolation recorded
    dep.violations          # [LockOrderViolation(cycle=("A","B"), ...)]

``TrackedLock`` wraps a real ``threading.Lock``/``RLock`` (or creates
one), so the protected code still genuinely excludes. A module-level
tracker (``get_tracker``/``reset_tracker``) lets a test fixture observe
locks created in code under test. The tracker never deadlocks the
program itself: detection is edge-graph reachability at acquire time,
and violations are *recorded* (and optionally raised) rather than
blocking.

Keyed by lock *class* (the name string), not instance — two instances
of the same class count as one node, matching the static rule's
``ClassName._attr`` identity.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class _Witness:
    """Where an edge was first observed."""

    holder: str
    acquired: str
    thread: str
    role: str = ""


# -- thread-role registry ---------------------------------------------
#
# Maps live threads to the static ``shared-state`` rule's role names
# ("evb", "solver-wave-loop", "ctrl", ...) so runtime findings — lock
# inversions here, write overlaps in ``racedep`` — attribute back to
# the same vocabulary the static report and ``--roles`` dump use.

_roles_mu = threading.Lock()
_thread_roles: Dict[int, str] = {}


def set_thread_role(role: str,
                    thread: Optional[threading.Thread] = None) -> None:
    """Register the static role name the given thread (default: the
    calling thread) runs as. Harnesses call this at thread entry."""
    ident = thread.ident if thread is not None else threading.get_ident()
    if ident is None:
        return
    with _roles_mu:
        _thread_roles[ident] = role


def clear_thread_roles() -> None:
    with _roles_mu:
        _thread_roles.clear()


def current_role() -> str:
    """The calling thread's registered role, else its thread name."""
    with _roles_mu:
        role = _thread_roles.get(threading.get_ident())
    return role if role else threading.current_thread().name


@dataclass
class LockOrderViolation:
    """An acquisition that closed a cycle in the learned order graph."""

    cycle: Tuple[str, ...]
    witness: _Witness
    prior: List[_Witness] = field(default_factory=list)

    def __str__(self) -> str:
        chain = " -> ".join(self.cycle + (self.cycle[0],))
        who = self.witness.thread
        if self.witness.role and self.witness.role != who:
            who = f"{who} (role {self.witness.role})"
        return (
            f"lock-order inversion {chain}: thread {who} "
            f"acquired {self.witness.acquired} while holding "
            f"{self.witness.holder}, but the reverse order was "
            "previously observed"
        )


class LockOrderError(RuntimeError):
    """Raised on inversion when the tracker is in raising mode."""


class LockDepTracker:
    """Learns held->acquired edges between lock classes and detects
    cycles at acquire time."""

    def __init__(self, raise_on_violation: bool = False) -> None:
        self._mu = threading.Lock()
        self._edges: Dict[Tuple[str, str], _Witness] = {}
        self._tls = threading.local()
        self.raise_on_violation = raise_on_violation
        self.violations: List[LockOrderViolation] = []

    # -- held-stack bookkeeping --------------------------------------

    def _stack(self) -> List[Tuple[str, bool]]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def on_acquire(self, name: str, reentrant: bool) -> None:
        stack = self._stack()
        violation: Optional[LockOrderViolation] = None
        tname = threading.current_thread().name
        role = current_role()
        with self._mu:
            for held, held_reentrant in stack:
                if held == name:
                    if reentrant and held_reentrant:
                        continue  # RLock recursion is the design
                    violation = LockOrderViolation(
                        cycle=(name,),
                        witness=_Witness(held, name, tname, role),
                    )
                    break
                path = self._path(name, held)
                if path is not None:
                    cycle = (held,) + tuple(path)
                    violation = LockOrderViolation(
                        cycle=cycle,
                        witness=_Witness(held, name, tname, role),
                        prior=[
                            self._edges[(a, b)]
                            for a, b in zip(path, path[1:])
                            if (a, b) in self._edges
                        ],
                    )
                    break
                self._edges.setdefault(
                    (held, name),
                    _Witness(held, name, tname, role),
                )
            if violation is not None:
                self.violations.append(violation)
        stack.append((name, reentrant))
        if violation is not None and self.raise_on_violation:
            raise LockOrderError(str(violation))

    def held(self) -> Tuple[str, ...]:
        """Lock classes the calling thread currently holds, outermost
        first. ``racedep`` reads this to stamp accesses."""
        return tuple(n for n, _ in self._stack())

    def on_release(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name:
                del stack[i]
                return

    # -- graph reachability (caller holds self._mu) ------------------

    def _path(self, src: str, dst: str) -> Optional[Tuple[str, ...]]:
        """Edge path src -> ... -> dst in the learned graph, or None."""
        if src == dst:
            return (src,)
        adj: Dict[str, List[str]] = {}
        for a, b in self._edges:
            adj.setdefault(a, []).append(b)
        seen = {src}
        frontier: List[Tuple[str, Tuple[str, ...]]] = [(src, (src,))]
        while frontier:
            node, path = frontier.pop()
            for nxt in adj.get(node, ()):
                if nxt == dst:
                    return path + (nxt,)
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append((nxt, path + (nxt,)))
        return None

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self.violations.clear()


class TrackedLock:
    """A real lock with lockdep instrumentation. Drop-in for the
    ``with``-statement and acquire/release protocols."""

    def __init__(
        self,
        name: str,
        lock: Optional[object] = None,
        reentrant: bool = False,
        tracker: Optional[LockDepTracker] = None,
    ) -> None:
        self.name = name
        self.reentrant = reentrant
        self._lock = lock if lock is not None else (
            threading.RLock() if reentrant else threading.Lock()
        )
        self._tracker = tracker if tracker is not None else get_tracker()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # record BEFORE blocking: lockdep's whole point is to flag the
        # inversion even when the deadlock doesn't strike this run
        self._tracker.on_acquire(self.name, self.reentrant)
        ok = self._lock.acquire(blocking, timeout)
        if not ok:
            self._tracker.on_release(self.name)
        return ok

    def release(self) -> None:
        self._lock.release()
        self._tracker.on_release(self.name)

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()


_global_tracker: Optional[LockDepTracker] = None
_global_mu = threading.Lock()


def get_tracker() -> LockDepTracker:
    global _global_tracker
    with _global_mu:
        if _global_tracker is None:
            _global_tracker = LockDepTracker()
        return _global_tracker


def reset_tracker() -> LockDepTracker:
    """Fresh module-level tracker (test fixtures call this)."""
    global _global_tracker
    with _global_mu:
        _global_tracker = LockDepTracker()
        return _global_tracker
