from openr_tpu.analysis.cli import main

raise SystemExit(main())
