"""AST analysis core: file loading, rule driver, suppressions, report.

The engine is a two-phase whole-tree pass:

1. **collect** — every rule sees every file and records global facts in
   the shared :class:`AnalysisContext` (donating dispatch signatures,
   annotated functions, lock classes, ...).
2. **check / finalize** — per-file findings, then cross-file findings
   (e.g. lock-order cycles) once the whole graph is known.

Suppression syntax (recorded, never silent)::

    x = risky()  # openr-lint: disable=donation-hazard -- reason here
    # openr-lint: disable=lock-order -- applies to the NEXT line
    # openr-lint: disable-file=retrace-risk -- whole file

A finding on line L is suppressed by a directive on L or on the
directive-only line immediately above. ``disable=all`` matches every
rule. The reason string after ``--`` is carried into the report so
``make lint-analysis`` output and the JSON artifact show *why* each
exception exists; a suppression without a reason is itself reported
(rule ``suppression-hygiene``) — prose-free exceptions are how
invariants rot.

No jax / numpy imports here: the pass must run in well under a second
on the whole tree (tier-1 runs it as a meta-test).
"""

from __future__ import annotations

import ast
import io
import os
import re
import time
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*openr-lint:\s*(disable|disable-file)="
    r"(?P<rules>[a-zA-Z0-9_,-]+)"
    r"(?:\s*--\s*(?P<reason>.*\S))?"
)

#: rule id for suppressions that carry no reason string
HYGIENE_RULE = "suppression-hygiene"
#: rule id for files the parser rejects
PARSE_RULE = "parse-error"
#: rule id for suppressions that no longer shield any finding
STALE_RULE = "suppression-stale"


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "suppressed": self.suppressed,
            "reason": self.reason,
        }

    def __str__(self) -> str:
        tag = f" [suppressed: {self.reason or 'NO REASON'}]" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}{tag}"


def _comment_lines(lines: Sequence[str]) -> Optional[frozenset]:
    """Line numbers (1-based) holding a real COMMENT token, so
    directive-shaped text inside string literals never registers. None
    when tokenization fails (unparseable file) — the caller falls back
    to the plain line scan."""
    try:
        return frozenset(
            tok.start[0]
            for tok in tokenize.generate_tokens(
                io.StringIO("\n".join(lines) + "\n").readline
            )
            if tok.type == tokenize.COMMENT
        )
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return None


class Suppressions:
    """Per-file ``# openr-lint:`` directive table."""

    def __init__(self, lines: Sequence[str]) -> None:
        # line (1-based) -> {rule -> (reason, directive line)}
        self.by_line: Dict[int, Dict[str, Tuple[str, int]]] = {}
        self.file_level: Dict[str, Tuple[str, int]] = {}
        # directive sites with no reason (line, rules) for hygiene
        self.missing_reason: List[Tuple[int, str]] = []
        # every directive site: (line, rule ids) — audited for
        # staleness (a directive shielding nothing is rot)
        self.sites: List[Tuple[int, Tuple[str, ...]]] = []
        comments = _comment_lines(lines)
        for i, raw in enumerate(lines, start=1):
            if comments is not None and i not in comments:
                # directive text inside a string literal (docstring
                # syntax examples) is not a directive
                continue
            m = _SUPPRESS_RE.search(raw)
            if m is None:
                continue
            rules = [r.strip() for r in m.group("rules").split(",") if r.strip()]
            reason = (m.group("reason") or "").strip()
            # a directive-only line may wrap its reason over further
            # comment-only lines; it shields the first CODE line below
            shield = None
            if raw.lstrip().startswith("#"):
                j = i + 1
                while j <= len(lines) and lines[j - 1].lstrip().startswith("#"):
                    cont = lines[j - 1].lstrip().lstrip("#").strip()
                    if reason and cont:
                        reason = f"{reason} {cont}"
                    j += 1
                shield = j
            if not reason:
                self.missing_reason.append((i, ",".join(rules)))
            self.sites.append((i, tuple(rules)))
            table = {r: (reason, i) for r in rules}
            if m.group(1) == "disable-file":
                self.file_level.update(table)
                continue
            self.by_line.setdefault(i, {}).update(table)
            if shield is not None:
                self.by_line.setdefault(shield, {}).update(table)

    def lookup(self, rule: str, line: int) -> Optional[str]:
        """Reason string (possibly empty) if suppressed, else None."""
        hit = self.lookup_site(rule, line)
        return hit[0] if hit is not None else None

    def lookup_site(
        self, rule: str, line: int
    ) -> Optional[Tuple[str, int, str]]:
        """(reason, directive line, matched rule id — ``rule`` or
        ``"all"``) if suppressed, else None. The directive line is what
        the staleness audit keys on."""
        for table in (self.by_line.get(line, {}), self.file_level):
            if rule in table:
                reason, dline = table[rule]
                return reason, dline, rule
            if "all" in table:
                reason, dline = table["all"]
                return reason, dline, "all"
        return None


class SourceFile:
    """One parsed module plus its suppression table."""

    def __init__(self, abspath: str, relpath: str) -> None:
        self.abspath = abspath
        self.path = relpath
        with open(abspath, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.suppressions = Suppressions(self.lines)
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.text, filename=relpath)
        except SyntaxError as exc:
            self.parse_error = f"{exc.msg} (line {exc.lineno})"

    # -- AST helpers shared by the rules ----------------------------

    def functions(self) -> Iterator[Tuple[ast.AST, Optional[str]]]:
        """Yield every (FunctionDef | AsyncFunctionDef, enclosing class
        name or None), including nested functions."""
        assert self.tree is not None

        def walk(node: ast.AST, cls: Optional[str]) -> Iterator:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    yield from walk(child, child.name)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    yield child, cls
                    yield from walk(child, cls)
                else:
                    yield from walk(child, cls)

        yield from walk(self.tree, None)

    def classes(self) -> Iterator[ast.ClassDef]:
        assert self.tree is not None
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                yield node


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def decorator_info(dec: ast.AST) -> Tuple[Optional[str], Optional[ast.Call]]:
    """(dotted decorator name, the Call node if the decorator is a
    call). ``@functools.partial(jax.jit, ...)`` reports the *partial
    target* name 'jax.jit' with the partial Call, so rules see through
    the standard jit idiom."""
    call = dec if isinstance(dec, ast.Call) else None
    name = dotted_name(dec.func if call is not None else dec)
    if (
        call is not None
        and name in ("functools.partial", "partial")
        and call.args
    ):
        inner = dotted_name(call.args[0])
        if inner is not None:
            return inner, call
    return name, call


def unwrap_aot_call(
    node: ast.Call,
) -> Optional[Tuple[str, List[ast.expr]]]:
    """See through ``aot_call(tag, fn, (dyn...), {statics})`` (the
    committed-dispatch executable cache, ops.aot_cache) and its
    impl-aware wrapper ``ell_dispatch`` (spf_sparse — same positional
    layout, the tag is re-keyed on the armed relax impl before the
    underlying aot_call): returns the wrapped dispatch's (dotted name,
    positional dyn-arg expressions) so call-site rules —
    donation-hazard, sharding-spec — keep their precision after a hot
    dispatch moves behind the AOT cache. The statics mapping is
    intentionally dropped: statics are hashable policy values (band
    tuples, n, k, mesh), never device buffers."""
    callee = dotted_name(node.func)
    if callee is None or callee.split(".")[-1] not in (
        "aot_call", "warm", "ell_dispatch",
    ):
        return None
    if len(node.args) < 3:
        return None
    inner = dotted_name(node.args[1])
    if inner is None:
        return None
    dyn = node.args[2]
    if not isinstance(dyn, (ast.Tuple, ast.List)):
        return None
    return inner, list(dyn.elts)


def call_kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def literal_or_none(node: Optional[ast.expr]):
    if node is None:
        return None
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


class Rule:
    """Base checker. Subclasses set ``id``/``description`` and override
    any of the three phases."""

    id: str = ""
    description: str = ""

    def collect(self, sf: SourceFile, ctx: "AnalysisContext") -> None:
        pass

    def check(
        self, sf: SourceFile, ctx: "AnalysisContext"
    ) -> Iterable[Finding]:
        return ()

    def finalize(self, ctx: "AnalysisContext") -> Iterable[Finding]:
        return ()


@dataclass
class AnalysisContext:
    """Whole-tree facts shared between phases. ``store`` is a per-rule
    scratch dict keyed by rule id."""

    root: str
    files: List[SourceFile] = field(default_factory=list)
    store: Dict[str, dict] = field(default_factory=dict)

    def scratch(self, rule_id: str) -> dict:
        return self.store.setdefault(rule_id, {})

    def file_for(self, relpath: str) -> Optional[SourceFile]:
        for sf in self.files:
            if sf.path == relpath:
                return sf
        return None


@dataclass
class Report:
    findings: List[Finding]
    files_scanned: int
    duration_s: float
    rules: List[str]

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def exit_code(self) -> int:
        return 1 if self.unsuppressed else 0

    def to_dict(self) -> Dict[str, object]:
        per_rule: Dict[str, int] = {r: 0 for r in self.rules}
        for f in self.findings:
            if not f.suppressed:
                per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        return {
            "files_scanned": self.files_scanned,
            "duration_s": round(self.duration_s, 3),
            "rules": list(self.rules),
            "findings_total": len(self.unsuppressed),
            "findings_suppressed": len(self.findings)
            - len(self.unsuppressed),
            "findings_per_rule": per_rule,
            "findings": [f.to_dict() for f in self.findings],
        }


def discover_files(root: str, targets: Sequence[str]) -> List[str]:
    """Python files under each target (file or directory), sorted,
    __pycache__ pruned."""
    out: List[str] = []
    for target in targets:
        path = target if os.path.isabs(target) else os.path.join(root, target)
        if os.path.isfile(path):
            out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def run_analysis(
    root: str,
    targets: Sequence[str] = ("openr_tpu",),
    rules: Optional[Sequence[Rule]] = None,
    audit_suppressions: bool = False,
) -> Report:
    """Run every rule over the tree; returns the full report (findings
    carry their suppression state — nothing is dropped silently).

    With ``audit_suppressions``, every directive that shielded no
    finding of a rule that RAN this pass is itself reported (rule
    ``suppression-stale``, unsuppressed — the audit's findings cannot
    be suppressed away): the code it excused has moved or been fixed,
    and a directive shielding nothing is how dead exceptions hide live
    regressions. Only meaningful on full-rule runs — a rule-subset run
    skips directives for rules that did not run."""
    if rules is None:
        from openr_tpu.analysis.rules import ALL_RULES

        rules = [cls() for cls in ALL_RULES]
    t0 = time.perf_counter()
    ctx = AnalysisContext(root=root)
    findings: List[Finding] = []
    for abspath in discover_files(root, targets):
        rel = os.path.relpath(abspath, root)
        sf = SourceFile(abspath, rel)
        if sf.parse_error is not None:
            findings.append(
                Finding(PARSE_RULE, rel, 1, 0, sf.parse_error)
            )
            continue
        ctx.files.append(sf)

    for rule in rules:
        for sf in ctx.files:
            rule.collect(sf, ctx)
    for rule in rules:
        for sf in ctx.files:
            findings.extend(rule.check(sf, ctx))
        findings.extend(rule.finalize(ctx))

    # suppression application + hygiene (a directive with no reason is
    # itself a finding so undocumented exceptions cannot accumulate)
    resolved: List[Finding] = []
    used_sites: set = set()  # (path, directive line, matched rule id)
    for f in findings:
        sf = ctx.file_for(f.path)
        if sf is not None:
            hit = sf.suppressions.lookup_site(f.rule, f.line)
            if hit is not None:
                reason, dline, matched = hit
                f.suppressed = True
                f.reason = reason
                used_sites.add((f.path, dline, matched))
    resolved.extend(findings)
    if audit_suppressions:
        ran = {r.id for r in rules}
        for sf in ctx.files:
            for dline, dir_rules in sf.suppressions.sites:
                for r in dir_rules:
                    if r != "all" and r not in ran:
                        continue  # rule did not run: cannot judge
                    if (sf.path, dline, r) in used_sites:
                        continue
                    resolved.append(
                        Finding(
                            STALE_RULE,
                            sf.path,
                            dline,
                            0,
                            f"suppression of '{r}' shields no finding "
                            "— the excused code moved or was fixed; "
                            "delete the directive",
                        )
                    )
    for sf in ctx.files:
        for line, rules_str in sf.suppressions.missing_reason:
            resolved.append(
                Finding(
                    HYGIENE_RULE,
                    sf.path,
                    line,
                    0,
                    f"suppression of '{rules_str}' carries no reason "
                    "string (append ' -- <why>')",
                )
            )
    resolved.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(
        findings=resolved,
        files_scanned=len(ctx.files),
        duration_s=time.perf_counter() - t0,
        rules=[r.id for r in rules],
    )
