"""Invariant lint engine: machine-checked hot-path rules.

PRs 1-3 made the churn path fast by imposing invariants that lived only
in prose (docs/ARCHITECTURE.md): residents are never donated into churn
dispatches (the retry-ladder hazard), ``PendingDelta`` is drained before
cold rebuilds, no host sync inside a solve window, every ``Tracer`` span
closes on all paths, and the per-module threads acquire locks in a
consistent order. This package turns each of those rules into a checker
that runs on every PR (``make lint-analysis`` / tier-1's meta-test):

- :mod:`openr_tpu.analysis.core` — the AST framework: per-file parse,
  rule registry, ``# openr-lint: disable=<rule> -- reason`` suppression
  syntax, and the two-phase (collect -> check -> finalize) driver that
  lets rules see the whole tree before reporting.
- :mod:`openr_tpu.analysis.annotations` — the runtime-inert marker API
  (``@solve_window``, ``@resident_buffers``, ``@requires_drain``,
  ``@donates``) the checkers key on. Importing it costs nothing on the
  hot path; the markers double as reviewer-facing documentation.
- :mod:`openr_tpu.analysis.rules` — the five checkers:
  ``donation-hazard``, ``host-sync-in-window``, ``lock-order``,
  ``span-discipline``, ``retrace-risk``.
- :mod:`openr_tpu.analysis.lockdep` — the runtime lock-order tracker
  (lockdep-style) that tests activate to catch dynamic inversions the
  static graph over-approximates; also home of the thread-role
  registry runtime findings attribute back to.
- :mod:`openr_tpu.analysis.racedep` — the runtime shared-state
  sanitizer pairing with the static ``shared-state`` rule: records
  (attr, thread, role, locks-held) access witnesses and convicts the
  first unlocked cross-thread write overlap without the race striking.

This package deliberately imports neither jax nor numpy: the static
pass must stay a sub-second pure-``ast`` walk.
"""

from openr_tpu.analysis.core import (  # noqa: F401
    AnalysisContext,
    Finding,
    Report,
    SourceFile,
    run_analysis,
)
from openr_tpu.analysis.rules import ALL_RULES  # noqa: F401
