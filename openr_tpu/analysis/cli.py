"""``python -m openr_tpu.analysis`` — run the invariant linters.

Exit status is the contract: 0 when every finding is suppressed (with a
reason), 1 otherwise — so ``make lint-analysis`` and tier-1 can gate on
it. ``--json`` additionally writes the machine-readable report (same
payload ``tools/lint_report.py`` wraps for CI artifacts).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

from openr_tpu.analysis.core import STALE_RULE, run_analysis
from openr_tpu.analysis.rules import ALL_RULES, SharedStateRule


def _default_root() -> str:
    # package lives at <root>/openr_tpu/analysis/cli.py
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m openr_tpu.analysis",
        description="openr-tpu invariant linters "
        "(--list-rules for the full registry)",
    )
    ap.add_argument(
        "targets",
        nargs="*",
        default=["openr_tpu"],
        help="files or directories relative to --root "
        "(default: openr_tpu)",
    )
    ap.add_argument(
        "--root",
        default=_default_root(),
        help="repository root (default: autodetected from the package)",
    )
    ap.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="RULE_ID",
        help="run only this rule (repeatable)",
    )
    ap.add_argument(
        "--json",
        metavar="PATH",
        help="write the machine-readable report here ('-' for stdout)",
    )
    ap.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print suppressed findings with their reasons",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    ap.add_argument(
        "--audit-suppressions",
        action="store_true",
        help="also report stale suppressions (directives shielding no "
        "finding of a rule that ran) as unsuppressable findings",
    )
    ap.add_argument(
        "--roles",
        action="store_true",
        help="dump the shared-state rule's inferred thread-role map "
        "(Class.method -> may-run-on roles) and exit",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.id:22s} {cls.description}")
        return 0

    rules = None
    if args.rules:
        known = {cls.id: cls for cls in ALL_RULES}
        unknown = [r for r in args.rules if r not in known]
        if unknown:
            print(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(known))})",
                file=sys.stderr,
            )
            return 2
        rules = [known[r]() for r in args.rules]

    if args.roles:
        role_rule = SharedStateRule()
        run_analysis(args.root, targets=args.targets, rules=[role_rule])
        for key in sorted(role_rule.role_map):
            print(f"{key}: {', '.join(role_rule.role_map[key])}")
        print(
            f"--roles: {len(role_rule.role_map)} role-carrying "
            "methods",
            file=sys.stderr,
        )
        return 0

    report = run_analysis(
        args.root,
        targets=args.targets,
        rules=rules,
        audit_suppressions=args.audit_suppressions,
    )

    shown: List[str] = []
    for f in report.findings:
        if f.suppressed and not args.show_suppressed:
            continue
        shown.append(str(f))
    for line in shown:
        print(line)
    n_sup = len(report.findings) - len(report.unsuppressed)
    stale = ""
    if args.audit_suppressions:
        n_stale = sum(1 for f in report.findings if f.rule == STALE_RULE)
        stale = f", {n_stale} stale suppression(s)"
    print(
        f"lint-analysis: {report.files_scanned} files, "
        f"{len(report.unsuppressed)} finding(s), "
        f"{n_sup} suppressed{stale}, {report.duration_s * 1000:.0f} ms",
        file=sys.stderr,
    )

    if args.json:
        payload = json.dumps(report.to_dict(), indent=2, sort_keys=True)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
    return report.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
