"""Typed daemon configuration.

Behavioral parity with the reference config layer
(``openr/if/OpenrConfig.thrift`` + ``openr/config/Config.h:34``): a typed
config struct parsed from JSON with constructor-time validation and
feature-flag helper accessors, passed immutably to every module. The
legacy-flag translation path (reference: GflagConfig,
openr/config/GflagConfig.h) is ``OpenrConfig.from_flags`` fed by the
argparse surface in ``openr_tpu.main``.
"""

from __future__ import annotations

import json
import re
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from openr_tpu.config.bgp_config import BgpConfig
from openr_tpu.types.lsdb import PrefixForwardingAlgorithm, PrefixForwardingType


class ConfigError(ValueError):
    pass


@dataclass
class AreaConfig:
    """reference: OpenrConfig.thrift AreaConfig."""

    area_id: str = "0"
    neighbor_regexes: List[str] = field(default_factory=lambda: [".*"])
    include_interface_regexes: List[str] = field(default_factory=lambda: [".*"])
    exclude_interface_regexes: List[str] = field(default_factory=list)

    def matches_neighbor(self, node_name: str) -> bool:
        return any(re.fullmatch(rx, node_name) for rx in self.neighbor_regexes)

    def matches_interface(self, if_name: str) -> bool:
        if any(
            re.fullmatch(rx, if_name) for rx in self.exclude_interface_regexes
        ):
            return False
        return any(
            re.fullmatch(rx, if_name) for rx in self.include_interface_regexes
        )


@dataclass
class SparkConfig:
    """reference: OpenrConfig.thrift SparkConfig."""

    hello_time_s: float = 20.0
    fastinit_hello_time_ms: int = 500
    handshake_time_ms: int = 500
    keepalive_time_s: float = 2.0
    hold_time_s: float = 10.0
    graceful_restart_time_s: float = 30.0
    mcast_port: int = 6666  # reference: Flags.cpp spark_mcast_port
    # "native" (framework codec) or "thrift" (the reference's
    # CompactProtocol SparkHelloPacket layout — interop with stock
    # Open/R neighbors on the LAN); receive always accepts both
    wire_format: str = "native"

    def validate(self) -> None:
        if self.wire_format not in ("native", "thrift"):
            raise ConfigError(
                f"spark wire_format must be native|thrift, got "
                f"{self.wire_format!r}"
            )
        if self.hold_time_s < 3 * self.keepalive_time_s:
            raise ConfigError(
                "spark hold_time must be >= 3x keepalive_time"
            )
        if self.graceful_restart_time_s < 3 * self.keepalive_time_s:
            raise ConfigError(
                "spark graceful_restart_time must be >= 3x keepalive_time"
            )


@dataclass
class KvStoreConfig:
    """reference: OpenrConfig.thrift KvstoreConfig."""

    key_ttl_ms: int = 300_000
    sync_interval_s: float = 60.0
    ttl_decrement_ms: int = 1
    enable_flood_optimization: bool = False
    is_flood_root: bool = False
    # reference: KvstoreFloodRate (0 = unlimited)
    flood_msg_per_sec: int = 0
    flood_msg_burst_size: int = 0
    # cross-process peer sync: TCP port the peer server binds (0 =
    # ephemeral; reference: Constants.h:257 kvstore port 60002) and
    # the wire spoken on it — the framework's own RPC codec, or thrift
    # framed CompactProtocol for interop with stock Open/R peers
    # (reference dual-stack flag: enable_kvstore_thrift,
    # KvStore.cpp:2940-2973)
    peer_port: int = 60002
    enable_kvstore_thrift: bool = False

    def flood_rate(self):
        if self.flood_msg_per_sec > 0 and self.flood_msg_burst_size > 0:
            return (float(self.flood_msg_per_sec),
                    self.flood_msg_burst_size)
        return None


@dataclass
class DecisionConfig:
    """reference: OpenrConfig.thrift DecisionConfig."""

    debounce_min_ms: int = 10
    debounce_max_ms: int = 250
    # reference default: true (Flags.cpp:39)
    enable_bgp_route_programming: bool = True


@dataclass
class LinkMonitorConfig:
    """reference: OpenrConfig.thrift LinkMonitorConfig."""

    linkflap_initial_backoff_ms: int = 60_000
    linkflap_max_backoff_ms: int = 300_000
    use_rtt_metric: bool = False


@dataclass
class WatchdogConfig:
    interval_s: float = 20.0
    thread_timeout_s: float = 300.0
    max_memory_mb: int = 800


@dataclass
class PrefixAllocationConfig:
    """reference: OpenrConfig.thrift PrefixAllocationConfig +
    Flags.cpp enable_prefix_alloc/seed_prefix/alloc_prefix_len/
    static_prefix_alloc/set_loopback_address/loopback_iface."""

    enabled: bool = False
    # "" means dynamic leaf mode: params learned from the
    # e2e-network-prefix KvStore key
    seed_prefix: str = ""
    alloc_prefix_len: int = 64
    static_allocation: bool = False
    set_loopback_addr: bool = False
    loopback_iface: str = "lo"

    def validate(self) -> None:
        if not self.enabled or self.static_allocation:
            return
        if self.seed_prefix:
            from openr_tpu.types import IpPrefix

            try:
                seed = IpPrefix.from_str(self.seed_prefix)
            except Exception as e:
                raise ConfigError(
                    f"bad seed_prefix {self.seed_prefix!r}: {e}"
                ) from e
            if self.alloc_prefix_len < seed.prefix_length:
                raise ConfigError(
                    "alloc_prefix_len shorter than the seed prefix"
                )
            addr_bits = 8 * len(seed.prefix_address.addr)
            if self.alloc_prefix_len > addr_bits:
                raise ConfigError(
                    f"alloc_prefix_len /{self.alloc_prefix_len} exceeds "
                    f"the seed's {addr_bits}-bit address width"
                )


@dataclass
class OpenrConfig:
    """reference: OpenrConfig.thrift OpenrConfig (314 lines)."""

    node_name: str = ""
    domain: str = "openr"
    areas: List[AreaConfig] = field(default_factory=lambda: [AreaConfig()])
    listen_addr: str = "::"
    openr_ctrl_port: int = 2018
    dryrun: bool = False
    enable_v4: bool = False
    enable_netlink_fib_handler: bool = False
    enable_ordered_fib_programming: bool = False
    enable_best_route_selection: bool = True
    enable_kvstore_request_queue: bool = False
    enable_watchdog: bool = True
    enable_lfa: bool = False
    # reference default: disabled (Flags.cpp enable_rib_policy)
    enable_rib_policy: bool = False
    # SR node-label election via per-area RangeAllocator when no static
    # node_label is configured (reference: Flags.cpp
    # enable_segment_routing + LinkMonitor.cpp:171)
    enable_segment_routing: bool = False
    prefix_forwarding_type: PrefixForwardingType = PrefixForwardingType.IP
    prefix_forwarding_algorithm: PrefixForwardingAlgorithm = (
        PrefixForwardingAlgorithm.SP_ECMP
    )
    per_prefix_keys: bool = True
    spark: SparkConfig = field(default_factory=SparkConfig)
    kvstore: KvStoreConfig = field(default_factory=KvStoreConfig)
    decision: DecisionConfig = field(default_factory=DecisionConfig)
    link_monitor: LinkMonitorConfig = field(default_factory=LinkMonitorConfig)
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    prefix_alloc: PrefixAllocationConfig = field(
        default_factory=PrefixAllocationConfig
    )
    persistent_store_path: str = "/tmp/openr_tpu_persistent_store.bin"
    node_label: int = 0
    solver_backend: str = "device"
    # shard the KSP2 engine's resident all-pairs state over ALL local
    # devices (ksp2_engine.set_engine_mesh at daemon start): the
    # engine's 12k single-chip activation bound scales with
    # sqrt(ndev). Off by default — a single-device mesh only adds
    # dispatch overhead.
    enable_solver_mesh: bool = False
    # BGP peering section (reference: openr/if/BgpConfig.thrift, gating
    # pluginStart at Main.cpp:595-601); None = BGP peering disabled
    bgp_config: Optional["BgpConfig"] = None

    # -- construction -----------------------------------------------------

    def __post_init__(self) -> None:
        self.validate()

    def validate(self) -> None:
        """reference: Config ctor validation (config/Config.h:34)."""
        if not self.node_name:
            raise ConfigError("node_name is required")
        if re.search(r"[\s:/]", self.node_name):
            raise ConfigError(
                "node_name must not contain whitespace, ':' or '/'"
            )
        if not self.areas:
            raise ConfigError("at least one area is required")
        area_ids = [a.area_id for a in self.areas]
        if len(area_ids) != len(set(area_ids)):
            raise ConfigError("duplicate area ids")
        self.spark.validate()
        self.prefix_alloc.validate()
        if (self.kvstore.flood_msg_per_sec > 0) != (
            self.kvstore.flood_msg_burst_size > 0
        ):
            raise ConfigError(
                "kvstore flood rate limiting needs BOTH "
                "flood_msg_per_sec and flood_msg_burst_size > 0 "
                f"(got {self.kvstore.flood_msg_per_sec}/"
                f"{self.kvstore.flood_msg_burst_size})"
            )
        if self.decision.debounce_min_ms > self.decision.debounce_max_ms:
            raise ConfigError("decision debounce min > max")
        if (
            self.prefix_forwarding_algorithm
            == PrefixForwardingAlgorithm.KSP2_ED_ECMP
            and self.prefix_forwarding_type != PrefixForwardingType.SR_MPLS
        ):
            raise ConfigError("KSP2_ED_ECMP requires SR_MPLS forwarding type")

    @staticmethod
    def from_dict(data: Dict) -> "OpenrConfig":
        def build(cls, value):
            if value is None:
                return cls()
            return cls(**value)

        kwargs = dict(data)
        if "areas" in kwargs:
            kwargs["areas"] = [AreaConfig(**a) for a in kwargs["areas"]]
        for key, cls in (
            ("spark", SparkConfig),
            ("kvstore", KvStoreConfig),
            ("decision", DecisionConfig),
            ("link_monitor", LinkMonitorConfig),
            ("watchdog", WatchdogConfig),
            ("prefix_alloc", PrefixAllocationConfig),
        ):
            if key in kwargs:
                kwargs[key] = build(cls, kwargs[key])
        if kwargs.get("bgp_config") is not None:
            kwargs["bgp_config"] = BgpConfig.from_dict(
                kwargs["bgp_config"]
            )
        if "prefix_forwarding_type" in kwargs and isinstance(
            kwargs["prefix_forwarding_type"], str
        ):
            kwargs["prefix_forwarding_type"] = PrefixForwardingType[
                kwargs["prefix_forwarding_type"]
            ]
        if "prefix_forwarding_algorithm" in kwargs and isinstance(
            kwargs["prefix_forwarding_algorithm"], str
        ):
            kwargs["prefix_forwarding_algorithm"] = PrefixForwardingAlgorithm[
                kwargs["prefix_forwarding_algorithm"]
            ]
        return OpenrConfig(**kwargs)

    @staticmethod
    def from_file(path: str) -> "OpenrConfig":
        with open(path) as f:
            return OpenrConfig.from_dict(json.load(f))

    def to_dict(self) -> Dict:
        out = asdict(self)
        out["prefix_forwarding_type"] = self.prefix_forwarding_type.name
        out["prefix_forwarding_algorithm"] = (
            self.prefix_forwarding_algorithm.name
        )
        return out

    # -- feature-flag helpers (reference: Config.h accessors) -------------

    def is_bgp_peering_enabled(self) -> bool:
        """reference: Config::isBgpPeeringEnabled — gates pluginStart
        (Main.cpp:595-601)."""
        return self.bgp_config is not None

    def area_for_neighbor(self, node_name: str) -> Optional[str]:
        for area in self.areas:
            if area.matches_neighbor(node_name):
                return area.area_id
        return None

    def area_ids(self) -> List[str]:
        return [a.area_id for a in self.areas]
