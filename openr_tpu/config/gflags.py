"""Legacy gflag translation shim.

The reference daemon is configured by ~99 gflags (reference:
openr/common/Flags.cpp) and migrates them onto the typed config via
``GflagConfig::createConfigFromGflag`` (reference:
openr/config/GflagConfig.h:38-120). This module is that shim for
openr-tpu: it parses the gflags command-line dialect (``--name=value``,
``--name value``, ``--name`` / ``--noname`` for bools) for the
load-bearing subset of the reference flag surface and builds an
:class:`~openr_tpu.config.config.OpenrConfig` from it, so an operator's
existing reference invocation of those flags works against this daemon
unchanged.

Every flag in ``GFLAG_DEFS`` is translated into the config.
Flags outside the subset (TLS, ZMQ ports, BGP peering internals) land
in ``GflagResult.unknown`` and are logged rather than rejected — the
reference tolerates unknown gflags the same way.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from openr_tpu.config.config import ConfigError, OpenrConfig

# name -> (type, default). The reference's defaults
# (openr/common/Flags.cpp); only flags that map onto our config or
# daemon surface are listed — everything else lands in `unknown`.
GFLAG_DEFS: Dict[str, Tuple[type, object]] = {
    # identity / topology
    "node_name": (str, ""),
    "domain": (str, "openr"),
    "areas": (str, ""),
    "listen_addr": (str, "*"),
    "openr_ctrl_port": (int, 2018),
    "spark_mcast_port": (int, 6666),
    # behavior toggles
    "dryrun": (bool, False),
    "enable_v4": (bool, False),
    "enable_netlink_fib_handler": (bool, False),
    "enable_ordered_fib_programming": (bool, False),
    "enable_lfa": (bool, False),
    "enable_bgp_route_programming": (bool, True),
    "enable_rib_policy": (bool, False),  # reference default: disabled
    "enable_segment_routing": (bool, False),
    "enable_watchdog": (bool, True),
    "enable_solver_mesh": (bool, False),
    "enable_flood_optimization": (bool, False),
    "is_flood_root": (bool, False),
    "enable_kvstore_thrift": (bool, False),
    "prefix_fwd_type_mpls": (bool, False),
    "prefix_algo_type_ksp2_ed_ecmp": (bool, False),
    # interfaces
    "iface_regex_include": (str, ""),
    "iface_regex_exclude": (str, ""),
    "loopback_iface": (str, "lo"),
    # kvstore
    "kvstore_key_ttl_ms": (int, 300_000),
    "kvstore_sync_interval_s": (int, 60),
    "kvstore_ttl_decrement_ms": (int, 1),
    "kvstore_flood_msg_per_sec": (int, 0),
    "kvstore_flood_msg_burst_size": (int, 0),
    # decision
    "decision_debounce_min_ms": (int, 10),
    "decision_debounce_max_ms": (int, 250),
    # link monitor
    "link_flap_initial_backoff_ms": (int, 60_000),
    "link_flap_max_backoff_ms": (int, 300_000),
    "enable_rtt_metric": (bool, True),
    # spark timers
    "spark2_hello_time_s": (int, 20),
    "spark2_hello_fastinit_time_ms": (int, 500),
    "spark2_handshake_time_ms": (int, 500),
    "spark2_heartbeat_time_s": (int, 2),
    "spark2_heartbeat_hold_time_s": (int, 10),
    # watchdog
    "watchdog_interval_s": (int, 20),
    "watchdog_threshold_s": (int, 300),
    "memory_limit_mb": (int, 800),
    # prefix allocation
    "enable_prefix_alloc": (bool, False),
    "seed_prefix": (str, ""),
    "alloc_prefix_len": (int, 64),
    "static_prefix_alloc": (bool, False),
    "per_prefix_keys": (bool, True),
    "set_loopback_address": (bool, False),
    # storage
    "config_store_filepath": (str, "/tmp/openr_tpu_persistent_store.bin"),
    "config": (str, ""),
}


@dataclass
class GflagResult:
    """Parsed legacy flags plus what they translate to."""

    flags: Dict[str, object]
    unknown: Dict[str, str] = field(default_factory=dict)

    def __getitem__(self, name: str):
        return self.flags[name]


def parse_gflags(argv: List[str]) -> GflagResult:
    """Parse the gflags dialect: ``--name=value``, ``--name value``,
    bools as ``--name`` / ``--name=true`` / ``--noname``."""
    flags = {name: default for name, (_, default) in GFLAG_DEFS.items()}
    unknown: Dict[str, str] = {}
    i = 0
    while i < len(argv):
        arg = argv[i]
        i += 1
        if not arg.startswith("--"):
            unknown[arg] = ""
            continue
        body = arg[2:]
        name, eq, inline = body.partition("=")
        name = name.replace("-", "_")
        negated = False
        if name not in GFLAG_DEFS and name.startswith("no"):
            stripped = name[2:]
            if (
                stripped in GFLAG_DEFS
                and GFLAG_DEFS[stripped][0] is bool
            ):
                name, negated = stripped, True
        if name not in GFLAG_DEFS:
            unknown[name] = inline
            continue
        ftype, _ = GFLAG_DEFS[name]
        if ftype is bool:
            if negated:
                flags[name] = False
            elif eq:
                flags[name] = inline.strip().lower() in (
                    "true", "1", "yes", "y",
                )
            else:
                flags[name] = True
            continue
        if eq:
            raw = inline
        elif i < len(argv) and not argv[i].startswith("--"):
            raw = argv[i]
            i += 1
        else:
            raise ConfigError(f"flag --{name} expects a value")
        try:
            flags[name] = ftype(raw)
        except ValueError as e:
            raise ConfigError(
                f"flag --{name}: cannot parse {raw!r} as "
                f"{ftype.__name__}"
            ) from e
    return GflagResult(flags=flags, unknown=unknown)


def config_from_gflags(result: GflagResult) -> OpenrConfig:
    """Build the typed config from parsed legacy flags (reference:
    GflagConfig::createConfigFromGflag)."""
    f = result.flags
    areas = [a for a in str(f["areas"]).split(",") if a] or ["0"]
    data = {
        "node_name": f["node_name"],
        "domain": f["domain"],
        "areas": [{"area_id": a} for a in areas],
        "listen_addr": (
            "::" if f["listen_addr"] == "*" else f["listen_addr"]
        ),
        "openr_ctrl_port": f["openr_ctrl_port"],
        "dryrun": f["dryrun"],
        "enable_v4": f["enable_v4"],
        "enable_netlink_fib_handler": f["enable_netlink_fib_handler"],
        "enable_ordered_fib_programming": f[
            "enable_ordered_fib_programming"
        ],
        "enable_lfa": f["enable_lfa"],
        "enable_rib_policy": f["enable_rib_policy"],
        "enable_segment_routing": f["enable_segment_routing"],
        "enable_watchdog": f["enable_watchdog"],
        "enable_solver_mesh": f["enable_solver_mesh"],
        "prefix_forwarding_type": (
            "SR_MPLS" if f["prefix_fwd_type_mpls"] else "IP"
        ),
        "prefix_forwarding_algorithm": (
            "KSP2_ED_ECMP"
            if f["prefix_algo_type_ksp2_ed_ecmp"]
            else "SP_ECMP"
        ),
        "per_prefix_keys": f["per_prefix_keys"],
        "prefix_alloc": {
            "enabled": f["enable_prefix_alloc"],
            "seed_prefix": f["seed_prefix"],
            "alloc_prefix_len": f["alloc_prefix_len"],
            "static_allocation": f["static_prefix_alloc"],
            "set_loopback_addr": f["set_loopback_address"],
            "loopback_iface": f["loopback_iface"],
        },
        "kvstore": {
            "key_ttl_ms": f["kvstore_key_ttl_ms"],
            "sync_interval_s": float(f["kvstore_sync_interval_s"]),
            "ttl_decrement_ms": f["kvstore_ttl_decrement_ms"],
            "enable_kvstore_thrift": f["enable_kvstore_thrift"],
            "enable_flood_optimization": f["enable_flood_optimization"],
            "is_flood_root": f["is_flood_root"],
            "flood_msg_per_sec": f["kvstore_flood_msg_per_sec"],
            "flood_msg_burst_size": f["kvstore_flood_msg_burst_size"],
        },
        "decision": {
            "debounce_min_ms": f["decision_debounce_min_ms"],
            "debounce_max_ms": f["decision_debounce_max_ms"],
            "enable_bgp_route_programming": f[
                "enable_bgp_route_programming"
            ],
        },
        "link_monitor": {
            "linkflap_initial_backoff_ms": f[
                "link_flap_initial_backoff_ms"
            ],
            "linkflap_max_backoff_ms": f["link_flap_max_backoff_ms"],
            "use_rtt_metric": f["enable_rtt_metric"],
        },
        "spark": {
            "hello_time_s": float(f["spark2_hello_time_s"]),
            "fastinit_hello_time_ms": f["spark2_hello_fastinit_time_ms"],
            "handshake_time_ms": f["spark2_handshake_time_ms"],
            "keepalive_time_s": float(f["spark2_heartbeat_time_s"]),
            "hold_time_s": float(f["spark2_heartbeat_hold_time_s"]),
            "mcast_port": f["spark_mcast_port"],
        },
        "watchdog": {
            "interval_s": float(f["watchdog_interval_s"]),
            "thread_timeout_s": float(f["watchdog_threshold_s"]),
            "max_memory_mb": f["memory_limit_mb"],
        },
        "persistent_store_path": f["config_store_filepath"],
    }
    iface_includes = [
        rx for rx in str(f["iface_regex_include"]).split(",") if rx
    ]
    iface_excludes = [
        rx for rx in str(f["iface_regex_exclude"]).split(",") if rx
    ]
    # reference default is NO interfaces (empty regex): an empty include
    # list here means "track nothing", not the AreaConfig match-all
    for area in data["areas"]:
        area["include_interface_regexes"] = iface_includes
        if iface_excludes:
            area["exclude_interface_regexes"] = iface_excludes
    return OpenrConfig.from_dict(data)


def load_config_from_argv(argv: List[str]) -> OpenrConfig:
    """One-call path: parse legacy argv and build the config. When
    ``--config`` names a file, the file is the sole config source and
    every other flag is ignored — exactly the reference's behavior
    (Main.cpp uses Config(FLAGS_config) and consults GflagConfig only
    when no file is given). Flags outside the translated subset are
    logged so a reference invocation that relies on them is visible."""
    import logging

    result = parse_gflags(argv)
    if result.unknown:
        logging.getLogger("openr_tpu.config.gflags").warning(
            "ignoring untranslated legacy flags: %s",
            ", ".join(sorted(result.unknown)),
        )
    if result.flags["config"]:
        return OpenrConfig.from_file(str(result.flags["config"]))
    return config_from_gflags(result)
