"""BGP peering configuration schema.

Models the reference's ``openr/if/BgpConfig.thrift`` (261 lines:
BgpPeerTimers:13, RouteLimit:22, AdvertiseLinkBandwidth:37, AddPath:49,
PeerGroup:56, BgpPeer:99, BgpConfig:211) as typed dataclasses with
constructor validation, JSON parsing, and the reference's peer-group
overlay semantics ("Peer Group name. peer config overwrites peer group
config", BgpConfig.thrift:201-203).

A registered plugin always starts with the daemon (the hook doubles as
the generic extension point, so non-BGP plugins exist); a BGP speaker
plugin receives this section through ``PluginArgs.bgp_config`` — None
when peering is disabled, so speakers must check before peering. The
reference instead gates ``pluginStart`` itself on BGP peering
(Main.cpp:595-601) because its plugin slot is BGP-only; the daemon
mirrors that intent by warning when peering is configured but no
plugin is registered to speak it (main.py).
"""

from __future__ import annotations

import enum
import ipaddress
from dataclasses import dataclass, field, fields, replace
from typing import Dict, List, Optional


class BgpConfigError(ValueError):
    pass


class AdvertiseLinkBandwidth(enum.IntEnum):
    """reference: BgpConfig.thrift:37-40."""

    NONE = 0
    AGGREGATE = 1


class AddPath(enum.IntEnum):
    """reference: BgpConfig.thrift:49-54."""

    NONE = 0
    RECEIVE = 1
    SEND = 2
    BOTH = 3


@dataclass(frozen=True)
class BgpPeerTimers:
    """reference: BgpConfig.thrift:13-20."""

    hold_time_seconds: int = 30
    keep_alive_seconds: int = 10
    out_delay_seconds: int = 0
    withdraw_unprog_delay_seconds: int = 0
    graceful_restart_seconds: Optional[int] = None
    graceful_restart_end_of_rib_seconds: Optional[int] = None

    def validate(self) -> None:
        if self.hold_time_seconds and self.keep_alive_seconds:
            if self.hold_time_seconds < 3 * self.keep_alive_seconds:
                raise BgpConfigError(
                    "bgp hold_time must be >= 3x keep_alive "
                    f"({self.hold_time_seconds} < "
                    f"3*{self.keep_alive_seconds})"
                )


@dataclass(frozen=True)
class RouteLimit:
    """reference: BgpConfig.thrift:22-29."""

    max_routes: int = 12000
    warning_only: bool = False
    warning_limit: int = 0


@dataclass(frozen=True)
class PeerGroup:
    """Shared defaults a peer can inherit by name.
    reference: BgpConfig.thrift:56-93."""

    name: str = ""
    description: Optional[str] = None
    remote_as: Optional[int] = None
    local_addr: Optional[str] = None
    next_hop4: Optional[str] = None
    next_hop6: Optional[str] = None
    enabled: Optional[bool] = None
    router_port_id: Optional[int] = None
    is_passive: Optional[bool] = None
    is_confed_peer: Optional[bool] = None
    is_rr_client: Optional[bool] = None
    next_hop_self: Optional[bool] = None
    remove_private_as: Optional[bool] = None
    disable_ipv4_afi: Optional[bool] = None
    disable_ipv6_afi: Optional[bool] = None
    bgp_peer_timers: Optional[BgpPeerTimers] = None
    peer_tag: Optional[str] = None
    local_as: Optional[int] = None
    advertise_link_bandwidth: Optional[AdvertiseLinkBandwidth] = None
    pre_filter: Optional[RouteLimit] = None
    post_filter: Optional[RouteLimit] = None
    enable_stateful_ha: Optional[bool] = None
    add_path: Optional[AddPath] = None


@dataclass(frozen=True)
class BgpPeer:
    """One BGP session.
    reference: BgpConfig.thrift:99-208 (field ids in comments there)."""

    peer_addr: str = ""  # address, or prefix for passive listen ranges
    remote_as: Optional[int] = None
    local_addr: Optional[str] = None
    next_hop4: Optional[str] = None
    next_hop6: Optional[str] = None
    description: Optional[str] = None
    is_passive: Optional[bool] = None
    is_confed_peer: Optional[bool] = None
    type: Optional[str] = None
    peer_id: Optional[str] = None
    is_rr_client: Optional[bool] = None
    peer_tag: Optional[str] = None
    next_hop_self: Optional[bool] = None
    disable_ipv4_afi: Optional[bool] = None
    disable_ipv6_afi: Optional[bool] = None
    router_port_id: Optional[int] = None
    bgp_peer_timers: Optional[BgpPeerTimers] = None
    enabled: Optional[bool] = None
    remove_private_as: Optional[bool] = None
    local_as: Optional[int] = None
    advertise_link_bandwidth: Optional[AdvertiseLinkBandwidth] = None
    pre_filter: Optional[RouteLimit] = None
    post_filter: Optional[RouteLimit] = None
    enable_stateful_ha: Optional[bool] = None
    peer_group_name: Optional[str] = None
    add_path: Optional[AddPath] = None

    def validate(self) -> None:
        if not self.peer_addr:
            raise BgpConfigError("bgp peer needs peer_addr")
        addr = self.peer_addr.split("/")[0]
        try:
            ipaddress.ip_address(addr)
        except ValueError as exc:
            raise BgpConfigError(
                f"bad bgp peer_addr {self.peer_addr!r}: {exc}"
            ) from exc
        if "/" in self.peer_addr and not self.is_passive:
            raise BgpConfigError(
                f"prefix peer_addr {self.peer_addr!r} only works for "
                "passive listening sessions (BgpConfig.thrift:108-112)"
            )
        if self.bgp_peer_timers is not None:
            self.bgp_peer_timers.validate()


# PeerGroup attributes a peer may inherit (everything shared by name)
_OVERLAY_FIELDS = tuple(
    f.name
    for f in fields(PeerGroup)
    if f.name not in ("name", "description")
)


def resolve_peer(peer: BgpPeer, groups: Dict[str, PeerGroup]) -> BgpPeer:
    """Overlay semantics: start from the named peer group's values, then
    let every explicitly-set peer field win (reference:
    BgpConfig.thrift:201 'peer config overwrites peer group config')."""
    if peer.peer_group_name is None:
        return peer
    group = groups.get(peer.peer_group_name)
    if group is None:
        raise BgpConfigError(
            f"peer {peer.peer_addr} names unknown peer group "
            f"{peer.peer_group_name!r}"
        )
    merged = {}
    for name in _OVERLAY_FIELDS:
        if getattr(peer, name) is None:
            inherited = getattr(group, name)
            if inherited is not None:
                merged[name] = inherited
    return replace(peer, **merged) if merged else peer


@dataclass(frozen=True)
class BgpConfig:
    """reference: BgpConfig.thrift:211-261."""

    router_id: str = ""
    local_as: int = 0
    peers: List[BgpPeer] = field(default_factory=list)
    hold_time: int = 30
    listen_port: int = 179
    local_confed_as: Optional[int] = None
    listen_addr: str = "::"
    cold_start_convergence_seconds: Optional[int] = None
    graceful_restart_convergence_seconds: Optional[int] = None
    peer_groups: List[PeerGroup] = field(default_factory=list)
    compute_ucmp_from_link_bandwidth_community: Optional[bool] = None
    eor_time_s: int = 45

    def __post_init__(self) -> None:
        self.validate()

    def validate(self) -> None:
        if not self.router_id:
            raise BgpConfigError("bgp config needs router_id")
        try:
            ipaddress.ip_address(self.router_id)
        except ValueError as exc:
            raise BgpConfigError(
                f"bad router_id {self.router_id!r}: {exc}"
            ) from exc
        if not (0 < self.local_as < 2 ** 32):
            raise BgpConfigError(f"bad local_as {self.local_as}")
        if not (0 < self.listen_port < 65536):
            raise BgpConfigError(f"bad listen_port {self.listen_port}")
        names = [g.name for g in self.peer_groups]
        if len(names) != len(set(names)):
            raise BgpConfigError("duplicate peer group names")
        groups = {g.name: g for g in self.peer_groups}
        seen = set()
        for peer in self.peers:
            if peer.peer_addr in seen:
                raise BgpConfigError(
                    f"duplicate bgp peer {peer.peer_addr}"
                )
            seen.add(peer.peer_addr)
            resolved = resolve_peer(peer, groups)
            resolved.validate()
            if resolved.remote_as is None:
                raise BgpConfigError(
                    f"peer {peer.peer_addr} has no remote_as (directly "
                    "or via its peer group)"
                )

    def resolved_peers(self) -> List[BgpPeer]:
        """Peers with their peer-group overlays applied."""
        groups = {g.name: g for g in self.peer_groups}
        return [resolve_peer(p, groups) for p in self.peers]

    # -- parsing -----------------------------------------------------------

    @staticmethod
    def from_dict(data: Dict) -> "BgpConfig":
        kwargs = dict(data)

        def build_timers(v):
            return BgpPeerTimers(**v) if isinstance(v, dict) else v

        def build_limit(v):
            return RouteLimit(**v) if isinstance(v, dict) else v

        def build_enum(cls, v):
            return cls[v] if isinstance(v, str) else (
                cls(v) if v is not None else None
            )

        def build_common(d: Dict) -> Dict:
            d = dict(d)
            if "bgp_peer_timers" in d:
                d["bgp_peer_timers"] = build_timers(d["bgp_peer_timers"])
            for key in ("pre_filter", "post_filter"):
                if key in d:
                    d[key] = build_limit(d[key])
            if "advertise_link_bandwidth" in d:
                d["advertise_link_bandwidth"] = build_enum(
                    AdvertiseLinkBandwidth, d["advertise_link_bandwidth"]
                )
            if "add_path" in d:
                d["add_path"] = build_enum(AddPath, d["add_path"])
            return d

        if "peers" in kwargs:
            kwargs["peers"] = [
                BgpPeer(**build_common(p)) for p in kwargs["peers"]
            ]
        if "peer_groups" in kwargs:
            kwargs["peer_groups"] = [
                PeerGroup(**build_common(g))
                for g in kwargs["peer_groups"]
            ]
        return BgpConfig(**kwargs)
