"""Synthetic topology generators: the framework's "model zoo".

Produces AdjacencyDatabase / PrefixDatabase sets for the same topology
families the reference benchmarks against (reference:
openr/decision/tests/RoutingBenchmarkUtils.cpp — createGrid:205,
createFabric:356) plus rings and random regular meshes for fuzzing.

All generators are deterministic given their arguments.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    BinaryAddress,
    IpPrefix,
    PrefixDatabase,
    PrefixEntry,
)
from openr_tpu.types.lsdb import PrefixForwardingAlgorithm, PrefixForwardingType


@dataclass
class Topology:
    """A fully-formed synthetic network: per-node adjacency + prefix DBs."""

    name: str
    area: str = "0"
    adj_dbs: Dict[str, AdjacencyDatabase] = field(default_factory=dict)
    prefix_dbs: Dict[str, PrefixDatabase] = field(default_factory=dict)

    @property
    def num_nodes(self) -> int:
        return len(self.adj_dbs)

    def nodes(self) -> List[str]:
        return sorted(self.adj_dbs)


def _iface(a: str, b: str, k: int = 0) -> str:
    # k numbers parallel links (LAG members) between the same pair;
    # k=0 keeps the historical single-link name
    return f"if_{a}_{b}" if k == 0 else f"if_{a}_{b}_{k}"


def _v6(node_idx: int, peer_idx: int) -> BinaryAddress:
    # unique link-local-style v6 address per directed link
    hi = (0xFE80 << 112) | (node_idx << 32) | peer_idx
    return BinaryAddress(addr=hi.to_bytes(16, "big"))


def _v4(node_idx: int, peer_idx: int) -> BinaryAddress:
    val = (10 << 24) | ((node_idx & 0xFFF) << 12) | (peer_idx & 0xFFF)
    return BinaryAddress(addr=val.to_bytes(4, "big"))


def _mk_adj(
    a: str,
    ai: int,
    b: str,
    bi: int,
    metric: int,
    adj_label: int = 0,
    overloaded: bool = False,
    link_idx: int = 0,
) -> Adjacency:
    return Adjacency(
        other_node_name=b,
        if_name=_iface(a, b, link_idx),
        other_if_name=_iface(b, a, link_idx),
        metric=metric,
        next_hop_v6=_v6(bi, ai),
        next_hop_v4=_v4(bi, ai),
        adj_label=adj_label,
        is_overloaded=overloaded,
    )


def _loopback_prefix(node_idx: int, v4: bool = False) -> IpPrefix:
    if v4:
        val = (172 << 24) | (16 << 16) | node_idx
        return IpPrefix(BinaryAddress(addr=val.to_bytes(4, "big")), 32)
    val = (0xFD00 << 112) | node_idx
    return IpPrefix(BinaryAddress(addr=val.to_bytes(16, "big")), 128)


def build_topology(
    name: str,
    edges: List[Tuple[str, str, int]],
    area: str = "0",
    forwarding_algorithm: PrefixForwardingAlgorithm = (
        PrefixForwardingAlgorithm.SP_ECMP
    ),
    forwarding_type: PrefixForwardingType = PrefixForwardingType.IP,
    node_labels: bool = True,
    v4_prefixes: bool = False,
) -> Topology:
    """Build a Topology from an undirected edge list (a, b, metric)."""
    names = sorted({n for e in edges for n in e[:2]})
    idx = {n: i for i, n in enumerate(names)}
    neighbors: Dict[str, List[Adjacency]] = {n: [] for n in names}
    # duplicate (a, b) pairs are PARALLEL links (LAG members): each
    # occurrence gets its own numbered interface pair so the LinkState
    # models them as first-class Links (reference: LinkState.h:82)
    pair_count: Dict[Tuple[str, str], int] = {}
    for a, b, metric in edges:
        pair = (a, b) if a < b else (b, a)
        k = pair_count.get(pair, 0)
        pair_count[pair] = k + 1
        neighbors[a].append(
            _mk_adj(a, idx[a], b, idx[b], metric, link_idx=k)
        )
        neighbors[b].append(
            _mk_adj(b, idx[b], a, idx[a], metric, link_idx=k)
        )

    topo = Topology(name=name, area=area)
    for n in names:
        topo.adj_dbs[n] = AdjacencyDatabase(
            this_node_name=n,
            adjacencies=tuple(neighbors[n]),
            node_label=idx[n] + 101 if node_labels else 0,
            area=area,
        )
        topo.prefix_dbs[n] = PrefixDatabase(
            this_node_name=n,
            prefix_entries=(
                PrefixEntry(
                    prefix=_loopback_prefix(idx[n], v4=v4_prefixes),
                    forwarding_algorithm=forwarding_algorithm,
                    forwarding_type=forwarding_type,
                ),
            ),
            area=area,
        )
    return topo


def grid(
    n: int,
    metric: int = 1,
    area: str = "0",
    forwarding_algorithm: PrefixForwardingAlgorithm = (
        PrefixForwardingAlgorithm.SP_ECMP
    ),
    forwarding_type: PrefixForwardingType = PrefixForwardingType.IP,
) -> Topology:
    """n x n grid. reference: RoutingBenchmarkUtils.cpp createGrid:205."""
    edges: List[Tuple[str, str, int]] = []

    def node(r: int, c: int) -> str:
        return f"node-{r * n + c}"

    for r in range(n):
        for c in range(n):
            if c + 1 < n:
                edges.append((node(r, c), node(r, c + 1), metric))
            if r + 1 < n:
                edges.append((node(r, c), node(r + 1, c), metric))
    return build_topology(
        f"grid-{n}x{n}",
        edges,
        area=area,
        forwarding_algorithm=forwarding_algorithm,
        forwarding_type=forwarding_type,
    )


def fat_tree(
    pods: int,
    ssw_per_plane: int = 4,
    fsw_per_pod: int = 4,
    rsw_per_pod: int = 12,
    area: str = "0",
    forwarding_algorithm: PrefixForwardingAlgorithm = (
        PrefixForwardingAlgorithm.SP_ECMP
    ),
    forwarding_type: PrefixForwardingType = PrefixForwardingType.IP,
) -> Topology:
    """3-tier fat-tree/fabric: spine (SSW) planes, fabric (FSW) per pod,
    rack (RSW) per pod. Wiring mirrors the reference fabric generator:
    every FSW k in a pod uplinks to every SSW in plane k; every RSW in a
    pod connects to every FSW in its pod.
    reference: RoutingBenchmarkUtils.h:53-58, createFabric:356.
    """
    edges: List[Tuple[str, str, int]] = []
    for pod in range(pods):
        for k in range(fsw_per_pod):
            fsw = f"fsw-{pod}-{k}"
            for s in range(ssw_per_plane):
                edges.append((f"ssw-{k}-{s}", fsw, 1))
            for rr in range(rsw_per_pod):
                edges.append((fsw, f"rsw-{pod}-{rr}", 1))
    return build_topology(
        f"fat-tree-p{pods}",
        edges,
        area=area,
        forwarding_algorithm=forwarding_algorithm,
        forwarding_type=forwarding_type,
    )


def fat_tree_nodes(
    target_nodes: int, **kwargs
) -> Topology:
    """Pick pod count so total node count is close to ``target_nodes``."""
    ssw_per_plane = kwargs.get("ssw_per_plane", 4)
    fsw_per_pod = kwargs.get("fsw_per_pod", 4)
    rsw_per_pod = kwargs.get("rsw_per_pod", 12)
    spine = ssw_per_plane * fsw_per_pod
    per_pod = fsw_per_pod + rsw_per_pod
    pods = max(1, round((target_nodes - spine) / per_pod))
    return fat_tree(pods, **kwargs)


def ring(n: int, metric: int = 1, area: str = "0") -> Topology:
    edges = [(f"node-{i}", f"node-{(i + 1) % n}", metric) for i in range(n)]
    return build_topology(f"ring-{n}", edges, area=area)


def random_mesh(
    n: int,
    degree: int = 4,
    seed: int = 0,
    max_metric: int = 100,
    area: str = "0",
) -> Topology:
    """Connected random graph with random metrics: the fuzzing workhorse."""
    rng = random.Random(seed)
    edges: List[Tuple[str, str, int]] = []
    seen = set()

    def add(i: int, j: int) -> None:
        if i == j:
            return
        key = (min(i, j), max(i, j))
        if key in seen:
            return
        seen.add(key)
        edges.append((f"node-{i}", f"node-{j}", rng.randint(1, max_metric)))

    # random spanning tree for connectivity
    order = list(range(n))
    rng.shuffle(order)
    for k in range(1, n):
        add(order[k], order[rng.randrange(k)])
    # extra random edges up to target degree
    target_edges = n * degree // 2
    attempts = 0
    while len(edges) < target_edges and attempts < 20 * target_edges:
        add(rng.randrange(n), rng.randrange(n))
        attempts += 1
    return build_topology(f"mesh-{n}-d{degree}-s{seed}", edges, area=area)
