"""Decision module: LSDB stream -> debounced route computation -> deltas.

Behavioral parity with the reference ``openr/decision/Decision.{h,cpp}``:

- subscribes to the KvStore publication queue; dispatches ``adj:`` /
  ``prefix:`` / ``fibtime:`` keys (processPublication, Decision.cpp:1722)
- maintains one LinkState per area plus the global PrefixState; per-prefix
  keys merge into a per-node synthetic PrefixDatabase
  (updateNodePrefixDatabase, Decision.cpp:1668)
- batches churn behind an AsyncDebounce (10..250 ms by default, matching
  common/Flags.cpp:87-96) and tracks whether the batch needs a *full*
  rebuild (any topology/node-label change, or local link-attribute
  change) or an *incremental* per-prefix pass
  (DecisionPendingUpdates, Decision.h:130; rebuildRoutes, Decision.cpp:1860)
- publishes DecisionRouteUpdate deltas on the route-updates queue with the
  batch's oldest perf-event chain attached
- cold-start hold gates the first route publication (Decision.cpp:1403)
- ordered-FIB hold decrement timer (Decision.cpp:1930 decrementOrderedFibHolds)

The solver behind it runs the TPU kernels (see spf_solver.py).
"""

from __future__ import annotations

import base64
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, Optional, Set, Tuple

from openr_tpu.decision.prefix_state import PrefixState
from openr_tpu.decision.rib import DecisionRouteDb, DecisionRouteUpdate
from openr_tpu.decision.spf_solver import SpfSolver, get_spf_counters
from openr_tpu.graph.linkstate import LinkState, LinkStateChange
from openr_tpu.messaging.queue import ReplicateQueue
from openr_tpu.types import (
    AdjacencyDatabase,
    IpPrefix,
    PerfEvents,
    Publication,
    PrefixDatabase,
    PrefixEntry,
)
from openr_tpu.analysis.annotations import (
    fault_boundary,
    solve_window,
    thread_confined,
)
from openr_tpu.faults.supervisor import DegradationSupervisor, HealthState
from openr_tpu.integrity import get_auditor, quarantine_active
from openr_tpu.load.admission import AdmissionControl
from openr_tpu.ops import dispatch_accounting as da
from openr_tpu.telemetry import (
    get_registry,
    get_tracer,
    install_default_triggers,
)
from openr_tpu.utils import keys as keyutil
from openr_tpu.utils import wire
from openr_tpu.utils.eventbase import AsyncDebounce, OpenrEventBase


class DecisionPendingUpdates:
    """reference: openr/decision/Decision.h:130."""

    def __init__(self, my_node_name: str):
        self._my_node_name = my_node_name
        self.count = 0
        self.perf_events: Optional[PerfEvents] = None
        self._needs_full_rebuild = False
        self.updated_prefixes: Set[IpPrefix] = set()
        # telemetry trace for the debounce window. The FIRST adopted
        # trace wins (publications arrive in order, so first == oldest
        # — the same convergence-from-earliest rule as perf_events);
        # later traces in the window are counted as merged and dropped.
        self.trace = None
        self._debounce_span = None

    def needs_full_rebuild(self) -> bool:
        return self._needs_full_rebuild

    def set_needs_full_rebuild(self) -> None:
        self._needs_full_rebuild = True

    def needs_route_update(self) -> bool:
        return self._needs_full_rebuild or bool(self.updated_prefixes)

    def apply_link_state_change(
        self,
        node_name: str,
        change: LinkStateChange,
        perf_events: Optional[PerfEvents] = None,
    ) -> None:
        self._needs_full_rebuild |= (
            change.topology_changed
            or change.node_label_changed
            # link attributes (nexthop addr / adj label) only matter for
            # our own links: they alter our programmed nexthops
            or (
                change.link_attributes_changed
                and node_name == self._my_node_name
            )
        )
        self._add_update(perf_events)

    def apply_prefix_state_change(
        self,
        changed: Set[IpPrefix],
        perf_events: Optional[PerfEvents] = None,
    ) -> None:
        self.updated_prefixes |= changed
        self._add_update(perf_events)

    def _add_update(self, perf_events: Optional[PerfEvents]) -> None:
        self.count += 1
        # keep the *oldest* event chain so convergence is measured from the
        # earliest update in the debounced batch
        if self.perf_events is None or (
            perf_events is not None
            and perf_events.events
            and self.perf_events.events
            and self.perf_events.events[0].unix_ts
            > perf_events.events[0].unix_ts
        ):
            self.perf_events = (
                PerfEvents(events=list(perf_events.events))
                if perf_events is not None
                else PerfEvents()
            )
            self.add_event("DECISION_RECEIVED")

    def add_event(self, descr: str) -> None:
        if self.perf_events is not None:
            self.perf_events.add(self._my_node_name, descr)

    def move_out_events(self) -> Optional[PerfEvents]:
        events = self.perf_events
        self.perf_events = None
        return events

    def adopt_trace(self, trace) -> None:
        if trace is None:
            return
        if self.trace is None:
            self.trace = trace
            self._debounce_span = trace.begin_span("decision.debounce")
        else:
            get_registry().counter_bump("telemetry.traces_merged")

    def move_out_trace(self):
        """End the debounce span and hand the trace to the rebuild."""
        trace, span = self.trace, self._debounce_span
        self.trace = None
        self._debounce_span = None
        if trace is not None and span is not None:
            trace.end_span(span, merged_updates=self.count)
            get_registry().observe(
                "decision.debounce_ms", span.dur_ms or 0.0
            )
        return trace

    def release_trace(self) -> None:
        """Reclaim an adopted trace that will never reach a rebuild
        (overload resets, teardown): the ``decision.debounce`` span MUST
        close on this path too, or sustained load leaks one open span
        per reset and the smoke gate's well-formedness check trips."""
        trace, span = self.trace, self._debounce_span
        self.trace = None
        self._debounce_span = None
        if trace is not None and span is not None:
            trace.end_span(span, aborted=True)
            get_registry().counter_bump("decision.debounce_spans_reclaimed")

    def reset(self) -> None:
        self.count = 0
        self.perf_events = None
        self._needs_full_rebuild = False
        self.updated_prefixes = set()
        self.release_trace()


# route_db is single-owner by mode, not by lock: eager mode mutates it
# on the event base; pipelined mode hands ownership to the emit worker,
# and every rebuild joins the worker (_drain_emit) before touching it.
@thread_confined("owner", "route_db")
class Decision:
    def __init__(
        self,
        my_node_name: str,
        kvstore_updates_queue: ReplicateQueue,
        route_updates_queue: ReplicateQueue,
        static_routes_queue: Optional[ReplicateQueue] = None,
        debounce_min_s: float = 0.010,
        debounce_max_s: float = 0.250,
        cold_start_s: float = 0.0,
        enable_v4: bool = False,
        compute_lfa_paths: bool = False,
        enable_ordered_fib: bool = False,
        bgp_dry_run: bool = False,
        enable_best_route_selection: bool = True,
        solver_backend: str = "device",
        enable_rib_policy: bool = True,
        admission: Optional[AdmissionControl] = None,
        pipelined_emit: bool = False,
        kvstore_reader_maxlen: Optional[int] = None,
        world_batch: Optional[bool] = None,
        view_cache_cap: Optional[int] = None,
        state_plane=None,
    ):
        # crash-safe state plane (openr_tpu.state.StatePlane): engine
        # warm material is snapshotted after each debounced rebuild and
        # warm_boot() rehydrates from its recover() result
        self._state_plane = state_plane
        # incident replay plane: a Decision that owns a state plane IS
        # the durable production pipeline, so its adopted post-CRDT
        # publications feed the flight recorder's event journal and its
        # WAL position anchors every post-mortem bundle. Memory-only
        # Decisions (tests, oracles) stay out of the shared journal.
        self._flight_journal = state_plane is not None
        if self._flight_journal:
            from openr_tpu.telemetry.flight import get_flight_recorder

            get_flight_recorder().set_anchor_provider(
                state_plane.flight_anchor
            )
        self._enable_rib_policy = enable_rib_policy
        self.my_node_name = my_node_name
        self.evb = OpenrEventBase(name=f"decision:{my_node_name}")
        self.route_updates_queue = route_updates_queue
        self.spf_solver = SpfSolver(
            my_node_name,
            enable_v4=enable_v4,
            compute_lfa_paths=compute_lfa_paths,
            enable_ordered_fib=enable_ordered_fib,
            bgp_dry_run=bgp_dry_run,
            enable_best_route_selection=enable_best_route_selection,
            backend=solver_backend,
            view_cache_cap=view_cache_cap,
            world_batch=world_batch,
        )
        # degradation ladder for the rebuild path: warm device solve →
        # device-state reset + cold rebuild → non-device backend. The
        # fallback backend is "native" when the configured backend is
        # the device (SpfView itself degrades native → host when the
        # toolchain is absent); for an already-host backend all rungs
        # run the same solve, which is harmless.
        self._primary_backend = solver_backend
        self._fallback_backend = (
            "native" if solver_backend == "device" else solver_backend
        )
        self.supervisor = DegradationSupervisor("decision")
        # standing anomaly set (p99 breach vs rolling baseline,
        # compile-after-warmup, reshard delta): always-on from the
        # moment a pipeline exists, idempotent across instances
        install_default_triggers()
        # monotonic stamp of the last route db installed while the
        # ladder was fully warm and no engine sat in integrity
        # quarantine — the staleness gauge ages from it while degraded
        self._last_good_route_ts: Optional[float] = None
        # the stamp is written by whichever role emits (event base or
        # the emit worker) and read by the registry's gauge thread —
        # a dedicated lock keeps the pair race-free without dragging
        # the gauge into the emit path's wider critical sections
        self._emit_mu = threading.Lock()
        get_registry().gauge(
            "decision.route_staleness_ms", self._route_staleness_ms
        )
        self.area_link_states: Dict[str, LinkState] = {}
        self.prefix_state = PrefixState()
        self.route_db = DecisionRouteDb()
        self.pending = DecisionPendingUpdates(my_node_name)
        self.fib_times: Dict[str, float] = {}
        self.rib_policy = None  # set via set_rib_policy
        self._enable_ordered_fib = enable_ordered_fib
        # per-node view assembled from per-prefix keys
        # (reference: perPrefixPrefixEntries_ / fullDbPrefixEntries_)
        self._per_prefix_entries: Dict[
            Tuple[str, str], Dict[IpPrefix, PrefixEntry]
        ] = {}
        self._full_db_entries: Dict[
            Tuple[str, str], Dict[IpPrefix, PrefixEntry]
        ] = {}
        self.counters: Dict[str, int] = {
            "decision.adj_db_update": 0,
            "decision.prefix_db_update": 0,
            "decision.route_build_runs": 0,
            "decision.publications": 0,
        }

        self._rebuild_debounced = AsyncDebounce(
            self.evb, debounce_min_s, debounce_max_s, self._on_debounce_fire
        )
        # debounce-terminal speculation latch: at most ONE speculative
        # view solve per debounce window (armed when the window
        # saturates, reset when the rebuild fires)
        self._spec_fired_this_window = False
        # admission/backpressure path (service plane): the controller
        # adapts the debounce ceiling to the reader backlog, and the
        # consume path sheds-by-coalescing once the backlog is deep
        self._admission = admission
        if self._admission is not None:
            self._admission.bind_debounce(
                self._rebuild_debounced, debounce_max_s
            )
        # pipelined emit: the diff/apply/publish tail of a rebuild runs
        # on a single-worker FIFO executor so event N+1's solve can
        # dispatch while event N's routes are still being derived and
        # programmed (PendingDelta double-buffering, one layer up). The
        # worker is the sole owner of route_db once enabled.
        self._pipelined_emit = pipelined_emit
        self._emit_executor: Optional[ThreadPoolExecutor] = (
            ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"decision-emit:{my_node_name}"
            )
            if pipelined_emit
            else None
        )
        self._emit_future: Optional[Future] = None
        self._cold_start_until = (
            time.monotonic() + cold_start_s if cold_start_s > 0 else 0.0
        )
        if cold_start_s > 0:
            self.evb.schedule_timeout(cold_start_s, self._on_cold_start_done)

        self._kv_reader = kvstore_updates_queue.get_reader(
            f"decision:{my_node_name}", maxlen=kvstore_reader_maxlen
        )
        self.evb.add_queue_reader(self._kv_reader, self._on_publication)
        if static_routes_queue is not None:
            self.evb.add_queue_reader(
                static_routes_queue.get_reader(f"decision:{my_node_name}"),
                self._on_static_routes,
            )
        self._ordered_fib_timer = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self.evb.run_in_thread()

    def stop(self) -> None:
        self.evb.stop()
        self.evb.join()
        if self._emit_executor is not None:
            if self._emit_future is not None:
                try:
                    self._emit_future.result(timeout=10.0)
                except Exception:  # noqa: BLE001 - drained best-effort
                    pass
                self._emit_future = None
            self._emit_executor.shutdown(wait=True)

    # -- queue handlers (run on the module thread) ------------------------

    def _on_publication(self, pub: Publication) -> None:
        if self._admission is not None:
            # admission path: observe backlog depth (adapting the
            # debounce ceiling) and, under a deep backlog, drain +
            # coalesce it into net-effect publications — superseded
            # per-key versions are shed, net state is untouched
            batch = self._admission.admit(pub, self._kv_reader)
            pubs, traces = batch.publications, batch.traces
            self.counters["decision.publications"] += batch.pubs_in
        else:
            pubs, traces = [pub], [pub.trace]
            self.counters["decision.publications"] += 1
        for p in pubs:
            self.process_publication(p)
        if self.pending.needs_route_update():
            # arrival order: the first (oldest) trace wins the window,
            # later ones are counted merged — same rule as perf_events
            for trace in traces:
                self.pending.adopt_trace(trace)
        else:
            for trace in traces:
                if trace is not None:
                    # publication with no route impact (e.g. fibtime
                    # keys): the trace dies here, visibly
                    get_registry().counter_bump(
                        "telemetry.traces_no_route_impact"
                    )
        if self.pending.needs_route_update():
            # overlap the device-side delta application with the
            # debounce window: the band scatter for this publication's
            # topology delta is enqueued asynchronously NOW, so by the
            # time the debounced rebuild dispatches its fused solve the
            # resident bands are already patched (and the previous
            # event's RouteDatabase delta emission ran concurrently
            # with the scatter instead of ahead of it)
            if self._admission is None or self._admission.allow_prewarm(
                self._kv_reader.size()
            ):
                self.spf_solver.prewarm(self.area_link_states)
            self._rebuild_debounced()
            # debounce-terminal speculation: once the window's backoff
            # saturates, further publications can only JOIN the window,
            # never extend it — the fire time is final, and under
            # latest-wins the current coalesced backlog is the most
            # likely rebuild composition. Stage its view solve now
            # (once per window) so the rebuild lands on a warm cache
            # hit; a later join supersedes the stage, counted
            # ops.spec_cancels, and the rebuild re-solves bit-identical.
            if (
                not self._spec_fired_this_window
                and self._rebuild_debounced.at_max_backoff()
            ):
                self._spec_fired_this_window = True
                self.spf_solver.speculate_views(
                    self.my_node_name, self.area_link_states
                )

    def _on_static_routes(self, delta) -> None:
        """Static MPLS routes pushed by the platform/plugin layer
        (reference: Decision static routes fiber)."""
        to_update = {
            r.top_label: list(r.next_hops)
            for r in getattr(delta, "mpls_routes_to_update", [])
        }
        to_delete = list(getattr(delta, "mpls_routes_to_delete", []))
        self.spf_solver.update_static_mpls_routes(to_update, to_delete)
        self.pending.set_needs_full_rebuild()
        self._rebuild_debounced()

    def process_publication(self, pub: Publication) -> None:
        """reference: Decision.cpp:1722 processPublication."""
        area = pub.area
        link_state = self.area_link_states.get(area)
        if link_state is None:
            link_state = self.area_link_states[area] = LinkState(area)

        for key, value in pub.key_vals.items():
            if value.value is None:
                continue  # ttl refresh only
            node_name = keyutil.get_node_name_from_key(key)
            try:
                if keyutil.is_adj_key(key):
                    adj_db = wire.loads(value.value, AdjacencyDatabase)
                    assert adj_db.this_node_name == node_name
                    if adj_db.area != area:
                        adj_db = AdjacencyDatabase(
                            this_node_name=adj_db.this_node_name,
                            is_overloaded=adj_db.is_overloaded,
                            adjacencies=adj_db.adjacencies,
                            node_label=adj_db.node_label,
                            area=area,
                            perf_events=adj_db.perf_events,
                        )
                    hold_up, hold_down = self._ordered_fib_holds(
                        link_state, node_name
                    )
                    self.counters["decision.adj_db_update"] += 1
                    self.pending.apply_link_state_change(
                        node_name,
                        link_state.update_adjacency_database(
                            adj_db, hold_up, hold_down
                        ),
                        adj_db.perf_events,
                    )
                    if self._flight_journal:
                        self._journal_adopted(area, key, value, pub)
                    if (
                        self._enable_ordered_fib
                        and link_state.has_holds()
                        and self._ordered_fib_timer is None
                    ):
                        self._schedule_ordered_fib_tick()
                elif keyutil.is_prefix_key(key):
                    prefix_db = wire.loads(value.value, PrefixDatabase)
                    assert prefix_db.this_node_name == node_name
                    node_db = self._update_node_prefix_db(
                        key, prefix_db, area
                    )
                    if node_db is None:
                        continue
                    self.counters["decision.prefix_db_update"] += 1
                    self.pending.apply_prefix_state_change(
                        self.prefix_state.update_prefix_database(node_db),
                        prefix_db.perf_events,
                    )
                    if self._flight_journal:
                        self._journal_adopted(area, key, value, pub)
                elif keyutil.is_fib_time_key(key):
                    try:
                        self.fib_times[node_name] = float(
                            value.value.decode()
                        )
                    except ValueError:
                        pass
            except Exception:  # noqa: BLE001 - bad LSDB values are skipped
                continue

        for key in pub.expired_keys:
            node_name = keyutil.get_node_name_from_key(key)
            if keyutil.is_adj_key(key):
                self.pending.apply_link_state_change(
                    node_name,
                    link_state.delete_adjacency_database(node_name),
                )
            elif keyutil.is_prefix_key(key):
                delete_db = PrefixDatabase(
                    this_node_name=node_name, delete_prefix=True, area=area
                )
                node_db = self._update_node_prefix_db(key, delete_db, area)
                if node_db is None:
                    continue
                self.pending.apply_prefix_state_change(
                    self.prefix_state.update_prefix_database(node_db)
                )

    def _journal_adopted(
        self, area: str, key: str, value, pub: Publication
    ) -> None:
        """Feed one adopted post-CRDT key into the flight recorder's
        event journal (the incident replay plane). The serialized value
        is the post-merge winner — replaying the journal over the
        bundle's anchor is exactly the state plane's recovery fold."""
        from openr_tpu.telemetry.flight import get_flight_recorder

        fr = get_flight_recorder()
        if not fr.enabled or value.value is None:
            return
        fr.journal_note(
            area,
            key,
            value_b64=base64.b64encode(value.value).decode("ascii"),
            version=value.version,
            originator=value.originator_id,
            trace_id=getattr(pub.trace, "trace_id", None),
        )

    def _update_node_prefix_db(
        self, key: str, prefix_db: PrefixDatabase, area: str
    ) -> Optional[PrefixDatabase]:
        """Merge a per-prefix or full-db advertisement into the node's
        synthetic PrefixDatabase (reference: Decision.cpp:1668
        updateNodePrefixDatabase)."""
        node = prefix_db.this_node_name
        slot = (node, area)
        parsed = keyutil.parse_per_prefix_key(key)
        if parsed is not None:
            _, _, prefix = parsed
            per = self._per_prefix_entries.setdefault(slot, {})
            if prefix_db.delete_prefix:
                per.pop(prefix, None)
            else:
                assert len(prefix_db.prefix_entries) == 1
                entry = prefix_db.prefix_entries[0]
                # ignore self-redistributed route reflection
                if (
                    node == self.my_node_name
                    and entry.area_stack
                    and entry.area_stack[-1] in self.area_link_states
                ):
                    return None
                per[prefix] = entry
        else:
            if prefix_db.delete_prefix:
                self._full_db_entries.pop(slot, None)
            else:
                self._full_db_entries[slot] = {
                    e.prefix: e for e in prefix_db.prefix_entries
                }

        per = self._per_prefix_entries.get(slot, {})
        full = self._full_db_entries.get(slot, {})
        entries = list(per.values()) + [
            e for p, e in full.items() if p not in per
        ]
        return PrefixDatabase(
            this_node_name=node,
            prefix_entries=tuple(entries),
            area=area,
            perf_events=prefix_db.perf_events,
        )

    # -- ordered fib holds ------------------------------------------------

    def _ordered_fib_holds(
        self, link_state: LinkState, node_name: str
    ) -> Tuple[int, int]:
        """Hold TTLs so farther routers program before nearer ones
        (RFC 6976 style; reference: Decision.cpp:1745-1752)."""
        if not self._enable_ordered_fib:
            return (0, 0)
        hops = link_state.get_hops_from_a_to_b(self.my_node_name, node_name)
        if hops is None:
            return (0, 0)
        hold_up = hops
        hold_down = max(0, link_state.get_max_hops_to_node(node_name) - hold_up)
        return (hold_up, hold_down)

    def _schedule_ordered_fib_tick(self) -> None:
        """Tick period = the slowest FIB in the network (reference:
        Decision.cpp:1943 getMaxFib, floor 1 ms)."""
        max_fib_s = max(self.fib_times.values(), default=1.0) / 1000.0
        self._ordered_fib_timer = self.evb.schedule_timeout(
            max(0.001, max_fib_s), self._decrement_ordered_fib_holds
        )

    def _decrement_ordered_fib_holds(self) -> None:
        """reference: Decision.cpp:1930 decrementOrderedFibHolds."""
        self._ordered_fib_timer = None
        still_has_holds = False
        topo_changed = False
        for link_state in self.area_link_states.values():
            change = link_state.decrement_holds()
            topo_changed |= change.topology_changed
            still_has_holds |= link_state.has_holds()
        if topo_changed:
            self.pending.set_needs_full_rebuild()
            self._rebuild_debounced()
        if still_has_holds:
            self._schedule_ordered_fib_tick()

    # -- rebuild ----------------------------------------------------------

    def _on_cold_start_done(self) -> None:
        self._cold_start_until = 0.0
        if self.pending.needs_route_update():
            self.rebuild_routes("COLD_START_UPDATE")

    def _on_debounce_fire(self) -> None:
        self._spec_fired_this_window = False
        self.rebuild_routes("DECISION_DEBOUNCE")
        # debounce terminal: close the journal's replay window — every
        # pub adopted since the previous mark rode THIS rebuild
        if self._flight_journal:
            from openr_tpu.telemetry.flight import get_flight_recorder

            get_flight_recorder().journal_mark(
                "wave",
                window="DECISION_DEBOUNCE",
                vantages=[self.my_node_name],
            )
        # snapshot AFTER the solve window closes: the capture reads the
        # resident distance rows back to host
        if self._state_plane is not None:
            self.checkpoint_state()
        # the audit plane rides the same post-converge hook — NEVER
        # inside rebuild_routes, where a probe dispatch would serialize
        # the solve window it is auditing. Audit errors are contained
        # inside the auditor (counted, never raised): the event loop
        # must not die for a probe.
        get_auditor().on_converge()

    def _route_staleness_ms(self) -> float:
        """How long the installed routes have been serving without a
        verified-good refresh: 0 while the ladder is warm and no engine
        is quarantined (or before the first install), else the age of
        the last route db installed in that state. Self-heal zeroes it."""
        with self._emit_mu:
            ts = self._last_good_route_ts
        if ts is None:
            return 0.0
        if (
            self.supervisor.state is HealthState.HEALTHY
            and not quarantine_active()
        ):
            return 0.0
        return (time.monotonic() - ts) * 1000.0

    def checkpoint_state(self) -> None:
        """Persist the engines' warm-start material to the state plane.

        Runs outside any solve window (one small device->host readback
        per area); failures are counted, never fatal — a crashed
        capture just means the next boot seeds cold for that area.
        """
        if self._state_plane is None:
            return
        from openr_tpu.state import capture_engine_snapshot

        for area, ls in self.area_link_states.items():
            try:
                snap = capture_engine_snapshot(area, ls)
                if snap is not None:
                    self._state_plane.record_engine_snapshot(snap)
            except Exception:  # noqa: BLE001 - capture is best-effort
                get_registry().counter_bump("state.capture_errors")
        # cadence-gated: the journal IS the crash record between cuts;
        # collapsing it on every converge would turn the WAL into a
        # full-LSDB write per event
        self._state_plane.maybe_checkpoint(only_if_due=True)

    def warm_boot(self, recovered) -> int:
        """Rehydrate from a ``StatePlane.recover()`` result.

        Rebuilds the per-area LinkStates from the journal-recovered
        LSDB, seeds the resident ELL engines from the persisted
        snapshots (digest-gated — a journal that advanced past a
        snapshot seeds cold, never wrong), and runs one rebuild so
        ``route_db`` is serveable and the first route update reaches
        Fib (ending its graceful-restart hold). Call BEFORE start().
        Returns the number of areas seeded warm.
        """
        from openr_tpu.state import rehydrate_engine

        tracer = get_tracer()
        trace = tracer.start("recovery.warm_boot", node=self.my_node_name)
        span = trace.begin_span("recovery.replay_lsdb")
        for area, key_vals in sorted(recovered.key_vals_by_area.items()):
            self.process_publication(
                Publication(key_vals=dict(key_vals), area=area)
            )
        trace.end_span(span, areas=len(recovered.key_vals_by_area))
        span = trace.begin_span("recovery.rehydrate_engines")
        warm = 0
        for area, ls in sorted(self.area_link_states.items()):
            if rehydrate_engine(ls, recovered.engine_snapshots.get(area)):
                warm += 1
        trace.end_span(
            span, warm=warm, areas=len(self.area_link_states)
        )
        span = trace.begin_span("recovery.rebuild")
        self.rebuild_routes("WARM_BOOT")
        trace.end_span(span)
        tracer.finish(trace, ok=True)
        get_registry().counter_bump("state.warm_boots")
        return warm

    @solve_window
    def rebuild_routes(self, event: str) -> None:
        """reference: Decision.cpp:1860 rebuildRoutes."""
        if self._cold_start_until and time.monotonic() < self._cold_start_until:
            return
        self.pending.add_event(event)
        self.counters["decision.route_build_runs"] += 1
        if self.pending.count > 1:
            # a debounce window folded several publications into THIS
            # one rebuild: downstream, the device churn path pays one
            # fused dispatch + one delta readback for the whole burst
            # (EllState merges the stacked patch journals; the route
            # engine takes the union affected set) — count the folds
            # so burst coalescing is observable next to
            # decision.route_build_runs
            get_registry().counter_bump(
                "decision.coalesced_publications",
                self.pending.count - 1,
            )

        # close the debounce span, open the rebuild span, and activate
        # the trace on this thread so deep call sites (the ELL
        # reconverge in ops.spf_sparse) can nest their own spans
        trace = self.pending.move_out_trace()
        tracer = get_tracer()
        rebuild_span = None
        full = self.pending.needs_full_rebuild()
        if trace is not None:
            rebuild_span = trace.begin_span(
                "decision.rebuild", full_rebuild=full
            )
            tracer.activate(trace)
        t_rebuild0 = time.perf_counter()

        # degradation ladder: warm solve with the configured backend →
        # reset all device-derived state and rebuild cold → flip to the
        # non-device backend. Every rung produces the same
        # DecisionRouteDb (the parity suite proves it per rung), so the
        # emitted delta is rung-independent. A LadderExhausted
        # propagates to the event loop after the finally closes the
        # trace span; pending is NOT reset on that path, so the next
        # publication retriggers the rebuild.
        payload = None
        win = None
        try:
            with da.event_window("decision.rebuild") as win:
                payload = self.supervisor.run(
                    (
                    (
                        "warm",
                        lambda: self._solve_update(
                            full,
                            reset=False,
                            backend=self._primary_backend,
                        ),
                    ),
                    (
                        "cold",
                        lambda: self._solve_update(
                            True,
                            reset=True,
                            backend=self._primary_backend,
                        ),
                    ),
                    (
                        "host",
                        lambda: self._solve_update(
                            True,
                            reset=True,
                            backend=self._fallback_backend,
                        ),
                    ),
                )
            )
        finally:
            get_registry().observe(
                "decision.rebuild_ms",
                (time.perf_counter() - t_rebuild0) * 1000.0,
            )
            if rebuild_span is not None and win is not None:
                # the committed-dispatch discipline, visible per
                # rebuild: 2 touches = one submit run + one reap run
                rebuild_span.attrs.update(
                    host_touches=win.touches,
                    host_dispatches=win.dispatches,
                    blocking_syncs=win.blocking_syncs,
                )
            if trace is not None:
                tracer.deactivate()
                if payload is None:
                    # ladder exhausted: no emit stage will run for this
                    # rebuild, so the span closes here
                    trace.end_span(
                        rebuild_span, routes_updated=-1, routes_deleted=-1
                    )

        self.pending.add_event("ROUTE_UPDATE")
        perf_events = self.pending.move_out_events()
        self.pending.reset()
        if self._emit_executor is not None:
            # double-buffered handoff: at most one emit in flight. The
            # wait lands AFTER this event's solve, so emit N overlapped
            # solve N+1; the single worker keeps route_db mutation and
            # queue pushes strictly FIFO.
            self._drain_emit()
            self._emit_future = self._emit_executor.submit(
                self._emit_update, payload, trace, rebuild_span, perf_events
            )
        else:
            self._emit_update(payload, trace, rebuild_span, perf_events)

    def _drain_emit(self) -> None:
        if self._emit_future is not None:
            try:
                self._emit_future.result()
            except Exception:  # noqa: BLE001 - counted, never kills evb
                get_registry().counter_bump("decision.emit_errors")
            self._emit_future = None

    def _emit_update(
        self, payload, trace, rebuild_span, perf_events
    ) -> None:
        """Emit stage of a rebuild: diff the solved db against the
        installed one, apply, and publish. In pipelined mode this runs
        on the single-worker emit executor (which then exclusively owns
        route_db); in eager mode it runs inline on the module thread."""
        kind, value = payload
        if kind == "db":
            # the diff runs HERE, not in the solve rung: route_db is
            # mutated by this stage, so reading it from the (possibly
            # concurrent) solve would race in pipelined mode
            update = self.route_db.calculate_update(value)
        else:
            update = value
        if trace is not None:
            trace.end_span(
                rebuild_span,
                routes_updated=len(update.unicast_routes_to_update),
                routes_deleted=len(update.unicast_routes_to_delete),
            )
        self.route_db.update(update)
        if (
            self.supervisor.state is HealthState.HEALTHY
            and not quarantine_active()
        ):
            with self._emit_mu:
                self._last_good_route_ts = time.monotonic()
        update.perf_events = perf_events
        update.trace = trace
        self.route_updates_queue.push(update)

    @fault_boundary
    def _solve_update(
        self, full: bool, reset: bool, backend: str
    ) -> Tuple[str, object]:
        """One ladder rung: compute this rebuild's routes. ``reset``
        drops every device-derived cache first (so a torn dispatch
        can't leak into the result); a backend flip does the same
        implicitly. A reset or flip forces the full-rebuild branch even
        for a per-prefix batch — the full route db is a superset of the
        per-prefix entries and the emit stage's ``calculate_update``
        diffs against the installed db, so the emitted delta is
        identical.

        Returns an emit payload — ``("db", DecisionRouteDb)`` for a
        full build (the emit stage diffs it against the installed db)
        or ``("delta", DecisionRouteUpdate)`` for the per-prefix
        incremental pass — so the rung itself never touches route_db
        and can overlap the previous event's emit."""
        flipped = self.spf_solver.backend != backend
        if reset:
            self.spf_solver.reset_device_state()
        if flipped:
            self.spf_solver.set_backend(backend)
        update = DecisionRouteUpdate()
        if full or reset or flipped:
            new_db = (
                self.spf_solver.build_route_db(
                    self.my_node_name, self.area_link_states, self.prefix_state
                )
                or DecisionRouteDb()
            )
            if self.rib_policy is not None and self.rib_policy.is_active():
                self.rib_policy.apply_policy(new_db.unicast_routes)
            return ("db", new_db)
        else:
            for prefix in self.pending.updated_prefixes:
                entry = self.spf_solver.create_route_for_prefix(
                    self.my_node_name,
                    self.area_link_states,
                    self.prefix_state,
                    prefix,
                )
                if entry is not None:
                    update.unicast_routes_to_update[prefix] = entry
                else:
                    update.unicast_routes_to_delete.append(prefix)
            if self.rib_policy is not None and self.rib_policy.is_active():
                change = self.rib_policy.apply_policy(
                    update.unicast_routes_to_update
                )
                update.unicast_routes_to_delete.extend(change.deleted_routes)
        return ("delta", update)

    # -- public (thread-safe) APIs ---------------------------------------

    def get_decision_route_db(
        self, node: Optional[str] = None
    ) -> DecisionRouteDb:
        """Compute (any-source!) routes on demand — first-class API, same
        solver as the hot path (reference: Decision.cpp:1492)."""
        node = node or self.my_node_name

        def compute() -> DecisionRouteDb:
            return (
                self.spf_solver.build_route_db(
                    node, self.area_link_states, self.prefix_state
                )
                or DecisionRouteDb()
            )

        return self.evb.call_and_wait(compute)

    def get_adj_dbs(self) -> Dict[str, Dict[str, AdjacencyDatabase]]:
        return self.evb.call_and_wait(
            lambda: {
                area: dict(ls.get_adjacency_databases())
                for area, ls in self.area_link_states.items()
            }
        )

    def get_received_route_count(self) -> int:
        return self.evb.call_and_wait(
            lambda: len(self.prefix_state.prefixes())
        )

    def set_rib_policy(self, policy) -> None:
        """Install a TTL'd policy; a rebuild is scheduled at expiry so its
        effects revert (reference: Decision.cpp:1600 setRibPolicy +
        ribPolicyTimer_). Inline validation mirrors the reference's
        thrift::OpenrError cases: feature knob off (Decision.cpp:1593)
        and an empty policy (DecisionTest RibPolicyError)."""
        if not self._enable_rib_policy:
            raise RuntimeError("rib policy feature is disabled by config")
        if policy is not None and not policy.statements:
            raise ValueError("rib policy must carry >= 1 statement")

        def install() -> None:
            self.rib_policy = policy
            self.pending.set_needs_full_rebuild()
            self._rebuild_debounced()
            if policy is not None:
                self.evb.schedule_timeout(
                    policy.get_ttl_remaining_s() + 0.001,
                    self._on_rib_policy_expiry,
                )

        self.evb.call_and_wait(install)

    def _on_rib_policy_expiry(self) -> None:
        if self.rib_policy is not None and not self.rib_policy.is_active():
            self.pending.set_needs_full_rebuild()
            self._rebuild_debounced()

    def get_rib_policy(self):
        if not self._enable_rib_policy:
            raise RuntimeError("rib policy feature is disabled by config")
        return self.evb.call_and_wait(lambda: self.rib_policy)

    def get_counters(self) -> Dict[str, int]:
        return self.evb.call_and_wait(self._collect_counters)

    def _collect_counters(self) -> Dict[str, int]:
        """Event counters + global gauges (reference: Decision.cpp:1964
        updateGlobalCounters)."""
        out = dict(self.counters)
        num_adjacencies = 0
        num_partial = 0
        nodes = set()
        for ls in self.area_link_states.values():
            num_adjacencies += ls.num_links
            spf = ls.get_spf_result(self.my_node_name) if ls.has_node(
                self.my_node_name
            ) else {}
            for name, adj_db in ls.get_adjacency_databases().items():
                nodes.add(name)
                num_links = len(ls.links_from_node(name))
                # partial adjacency: declared but not bidirectional, only
                # counted for reachable, non-isolated nodes
                if name in spf and num_links != 0:
                    num_partial += max(
                        0, len(adj_db.adjacencies) - num_links
                    )
        conflicting = sum(
            1
            for entries in self.prefix_state.prefixes().values()
            if PrefixState.has_conflicting_forwarding_info(entries)
        )
        out["decision.num_conflicting_prefixes"] = conflicting
        out["decision.num_partial_adjacencies"] = num_partial
        out["decision.num_complete_adjacencies"] = num_adjacencies
        out["decision.num_nodes"] = max(len(nodes), 1)
        out["decision.num_prefixes"] = len(self.prefix_state.prefixes())
        out.update(get_spf_counters())
        return out
