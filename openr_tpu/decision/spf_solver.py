"""SpfSolver: per-prefix best-route selection and next-hop computation.

Behavioral parity with the reference ``openr/decision/Decision.cpp``
SpfSolverImpl (buildRouteDb:569, createRouteForPrefix:402,
selectBestRoutes:737, maybeFilterDrainedNodes:783, selectBestPathsSpf:847,
selectBestPathsKsp2:908, addBestPaths:1033, getNextHopsWithMetric:1124,
getNextHopsThrift:1211) — re-architected so the graph math runs on TPU:

- shortest-path distances and ECMP first-hop sets come from the batched
  kernels in ``openr_tpu.ops.spf`` over the area's compiled
  ``GraphSnapshot`` ("device" backend), or from the host Dijkstra oracle
  ("host" backend; both are parity-tested against each other);
- per-prefix selection/filtering logic stays host-side where the data is
  ragged (it is cheap: O(advertisers) per prefix).

KSP2_ED_ECMP path enumeration uses host-side backtracing over SPF
predecessor links (paths are short; the SPF runs behind them are memoized).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from openr_tpu.analysis.annotations import thread_confined
from openr_tpu.decision.prefix_state import NodeAndArea, PrefixEntries, PrefixState
from openr_tpu.decision.rib import DecisionRouteDb, RibMplsEntry, RibUnicastEntry
from openr_tpu.faults.injector import fault_point, register_fault_site
from openr_tpu.graph.linkstate import Link, LinkState
from openr_tpu.graph.snapshot import INF, GraphSnapshot, SnapshotCache
from openr_tpu.types import (
    BinaryAddress,
    IpPrefix,
    MplsAction,
    MplsActionCode,
    NextHop,
    PrefixEntry,
    PrefixType,
)
from openr_tpu.types.lsdb import PrefixForwardingAlgorithm, PrefixForwardingType
from openr_tpu.utils.constants import is_mpls_label_valid

Metric = int
AreaLinkStates = Dict[str, LinkState]


def make_next_hop(
    address: BinaryAddress,
    if_name: Optional[str],
    metric: Metric,
    mpls_action: Optional[MplsAction] = None,
    area: Optional[str] = None,
    neighbor_node_name: Optional[str] = None,
) -> NextHop:
    """reference: openr/common/Util.cpp createNextHop"""
    if if_name is not None:
        address = BinaryAddress(addr=address.addr, if_name=if_name)
    return NextHop(
        address=address,
        metric=int(metric),
        mpls_action=mpls_action,
        area=area,
        neighbor_node_name=neighbor_node_name,
    )


@dataclass
class BestRouteSelectionResult:
    """reference: openr/decision/Decision.h BestRouteSelectionResult"""

    success: bool = False
    all_node_areas: Set[NodeAndArea] = field(default_factory=set)
    best_node_area: NodeAndArea = ("", "")

    def has_node(self, node: str) -> bool:
        return any(n == node for n, _ in self.all_node_areas)


def select_best_prefix_metrics(entries: PrefixEntries) -> Set[NodeAndArea]:
    """Pick advertisers with the best (path_pref DESC, source_pref DESC,
    distance ASC) metrics. The initial best is (0, 0, 0): advertisements
    strictly worse than the zero-metric tuple select nothing — matching the
    reference exactly. reference: openr/common/Util.h:549."""
    best_tuple = (0, 0, 0)
    best_keys: Set[NodeAndArea] = set()
    for key, entry in entries.items():
        t = entry.metrics.comparison_key()
        if t < best_tuple:
            continue
        if t > best_tuple:
            best_tuple = t
            best_keys.clear()
        best_keys.add(key)
    return best_keys


def select_best_node_area(
    all_node_areas: Set[NodeAndArea], my_node_name: str
) -> NodeAndArea:
    """Deterministic representative: self if present, else smallest key.
    reference: openr/common/Util.cpp:1057."""
    ordered = sorted(all_node_areas)
    for node_area in ordered:
        if node_area[0] == my_node_name:
            return node_area
    return ordered[0]


def get_prefix_forwarding_type_and_algorithm(
    entries: PrefixEntries, best_node_areas: Set[NodeAndArea]
) -> Tuple[PrefixForwardingType, PrefixForwardingAlgorithm]:
    """Lowest-common-denominator forwarding config among best advertisers.
    reference: openr/common/Util.cpp:617."""
    if not entries:
        return (PrefixForwardingType.IP, PrefixForwardingAlgorithm.SP_ECMP)
    ftype = PrefixForwardingType.SR_MPLS
    falgo = PrefixForwardingAlgorithm.KSP2_ED_ECMP
    for node_area, entry in entries.items():
        if node_area not in best_node_areas:
            continue
        ftype = min(ftype, entry.forwarding_type)
        falgo = min(falgo, entry.forwarding_algorithm)
        if (
            ftype == PrefixForwardingType.IP
            and falgo == PrefixForwardingAlgorithm.SP_ECMP
        ):
            break
    return (ftype, falgo)


# Alternate solver backends registered by plugins (the north-star
# "drop-in SpfSolver implementation" hook; reference: the pluginStart
# registration point, openr/plugin/Plugin.h:24-34). A factory takes
# (link_state, root) and returns an object implementing the SpfView
# query protocol: is_reachable / metric_to / next_hops_toward /
# metric_between.
_SPF_BACKENDS: Dict[str, "Callable[[LinkState, str], object]"] = {}


def register_spf_backend(name: str, factory) -> None:
    """Register a custom SPF view backend usable as
    ``SpfSolver(..., backend=name)``. Built-in names ("device", "native",
    "host") cannot be overridden."""
    assert name not in ("device", "native", "host"), name
    _SPF_BACKENDS[name] = factory


def unregister_spf_backend(name: str) -> None:
    _SPF_BACKENDS.pop(name, None)


# above this node count the device backend switches from the dense
# snapshot (O(N^2) metric matrix) to the resident sliced-ELL kernel
SPARSE_NODE_THRESHOLD = 4096

# Solver observability (exported through Decision.get_counters).
# Process-global by design, mirroring the reference's fb303 counter
# singletons (fb303::fbData->addStatValue) — and the ELL resident cache
# these count against is itself process-global device state. The host
# fallback counter tracks SpfView.metric_between queries answered by a
# full host Dijkstra because the queried source was outside the device
# batch — at scale that is an O(N log N) cliff that must stay at zero on
# the hot path (round-1 review: silent fallback).
# Since the telemetry spine landed this is a registry-backed shim: the
# same `SPF_COUNTERS[k] += 1` / `dict(SPF_COUNTERS)` call sites, but
# the store of record is openr_tpu.telemetry's process-wide Registry,
# so OpenrCtrl.get_counters / breeze / bench artifacts see these names
# without a per-module merge loop.
from openr_tpu.telemetry import get_registry as _get_registry

SPF_COUNTERS = _get_registry().counter_dict(
    [
        "decision.spf_host_fallback",
        "decision.ell_full_compiles",
        "decision.ell_patches",
        "decision.ksp2_device_batches",
        "decision.ksp2_host_fallbacks",
        "decision.ksp2_cold_builds",
        "decision.ksp2_incremental_syncs",
        "decision.ksp2_warm_dispatches",
        # speculative fast path could not run mesh-wide (mask budget,
        # empty batch, ...): typed so dashboards and the runbook can
        # alert on silent single-chip drops under sharding
        "decision.ksp2.spec_mesh_fallbacks",
        "decision.ksp2_affected_dsts",
        "decision.ksp2_route_reuses",
        "decision.sp_route_reuses",
        "decision.ell_prewarms",
        "decision.device_state_resets",
        "decision.backend_switches",
        # multi-area batched dispatch (ops.world_batch): builds whose
        # area views were solved as one tenant-plane dispatch, and
        # preload attempts that fell back to sequential solves
        "decision.world_preloads",
        "decision.world_preload_failures",
        # SpfSolver._views LRU demotions — the miniature of
        # tenancy.evictions; a hot loop here means the view cache cap
        # (OPENR_VIEW_CACHE_CAP) is below the live area count
        "route_engine.view_evictions",
    ]
)

# SpfSolver._views LRU capacity (graphs, not views). Overridable per
# solver via the view_cache_cap constructor arg.
VIEW_CACHE_CAP_DEFAULT = int(
    os.environ.get("OPENR_VIEW_CACHE_CAP", "4") or 4
)

# the Decision degradation ladder's injection seam (a fresh device
# view solve; see openr_tpu.faults)
FAULT_SPF_SOLVE = register_fault_site("decision.spf_solve")

# KSP2 device prefetch: below this many KSP2 destinations the host path
# is cheaper than a device dispatch; batches are fixed-size so the
# masked kernel compiles once per (topology bands, chunk) shape.
KSP2_DEVICE_MIN_DSTS = 32
# the masked kernel iterates one relaxation per hop: on low-diameter
# fabrics (fat-tree: 4-6 hops) one dispatch replaces N host Dijkstras
# (measured 5.4x at 1k nodes), but on a 31x31 grid (60 hops) the
# iteration count hands the win back to host Dijkstra — gate on the
# root's hop eccentricity from the unit-metric SPF
KSP2_DEVICE_MAX_HOPS = 16
# mask-memory budget per dispatch (bool slots); the chunk adapts so
# small graphs take ONE dispatch (readbacks ride a ~69ms relay RTT
# each) while 10k+-node graphs stay within device memory
KSP2_DEVICE_MASK_BUDGET = 32_000_000


def _ksp2_chunk(graph) -> int:
    # grow from 1 so the budget holds even when a single chunk of 32
    # bool masks would already exceed it at extreme ELL slot counts
    slots = sum(band.rows * band.k for band in graph.bands)
    chunk = 1
    while (
        chunk < 1024
        and chunk * 2 * max(1, slots) <= KSP2_DEVICE_MASK_BUDGET
    ):
        chunk *= 2
    return chunk



import weakref as _weakref

# weakly keyed by the LIVE LinkState: an id()-keyed memo can serve a
# dead graph's signature when CPython recycles the address for a new
# LinkState whose version counters pass through the same values — the
# SP-reuse soak caught exactly that as a parity break across worlds
_LINKS_SIG_MEMO: "_weakref.WeakKeyDictionary" = (
    _weakref.WeakKeyDictionary()
)

_EMPTY_PREFIXES: frozenset = frozenset()


def _local_links_sig(ls: LinkState, node: str) -> tuple:
    """Signature of every route input read off the root's own links
    during next-hop materialization (Decision.cpp:1211): iface, metric,
    peer, liveness, v6/v4 next-hop addresses. Shared by the node-label
    and SP-reuse caches so their invalidation can't drift apart.

    Memoized per live graph x (topology version, attribute version,
    node): every field below moves one of the two versions when it
    changes, so both caches' per-build probes share one link walk."""
    per_ls = _LINKS_SIG_MEMO.get(ls)
    if per_ls is None:
        per_ls = {}
        _LINKS_SIG_MEMO[ls] = per_ls
    key = (ls.topology_version, ls.attributes_version, node)
    sig = per_ls.get(key)
    if sig is None:
        while len(per_ls) > 32:  # a few roots x live versions
            per_ls.pop(next(iter(per_ls)))
        sig = tuple(
            (
                link.iface_from(node),
                link.metric_from(node),
                link.other_node(node),
                link.is_up(),
                link.nh_v6_from(node).addr,
                link.nh_v4_from(node).addr,
            )
            for link in ls.ordered_links_from_node(node)
        )
        per_ls[key] = sig
    return sig


def get_spf_counters() -> Dict[str, int]:
    out = dict(SPF_COUNTERS)
    # sharded-dispatch placement/readback counters: surfaced in the
    # same snapshot so bench artifacts and the reshard-storm runbook
    # recipe read one merged view (0 when no mesh ever activated)
    _reg = _get_registry()
    for _k in (
        "ops.reshard_events", "ops.shard_readback_bytes",
        # committed-dispatch accounting: submit/reap discipline of the
        # churn windows plus the AOT executable cache's hit economics
        "ops.host_dispatches", "ops.blocking_syncs",
        "ops.async_reaps", "ops.aot_compiles", "ops.aot_hits",
        "ops.aot_fallbacks",
    ):
        out[_k] = _reg.counter_get(_k)
    # fold in the ops-level resident-band counters under the same
    # namespace (one merged view for Decision.get_counters and the
    # churn smoke test)
    try:
        from openr_tpu.ops.spf_sparse import ELL_COUNTERS
    except Exception:
        return out
    for k, v in ELL_COUNTERS.items():
        out["decision." + k] = v
    return out


class SpfView:
    """SPF results for one area as seen from one root node.

    Device backend: distances + ECMP first-hop matrix from the jitted
    kernels over the area snapshot (dense for moderate N, sparse
    edge-list past SPARSE_NODE_THRESHOLD). Host backend: the Dijkstra
    oracle.
    """

    def __init__(self, ls: LinkState, root: str, backend: str):
        self._ls = ls
        self._root = root
        if backend == "native":
            from openr_tpu.graph import native_spf

            if not native_spf.is_available():
                backend = "host"  # toolchain missing: degrade gracefully
        self._backend = backend
        if backend == "device":
            if (
                len(ls.get_adjacency_databases()) > SPARSE_NODE_THRESHOLD
                # a batched tenant-plane dispatch (or a KSP2 engine)
                # already solved this exact view: consume it instead
                # of building the dense snapshot
                or _ELL_RESIDENT.has_preloaded(ls, root)
            ):
                self._init_device_sparse()
            else:
                self._init_device()
        elif backend == "native":
            self._init_native()
        else:
            self._init_host()

    # -- device backend ---------------------------------------------------

    def _init_device(self) -> None:
        """Batched {source} + neighbors SPF: the only rows a route rebuild
        consumes (source distances for best-path selection, neighbor rows
        for ECMP first hops and LFA — reference: Decision.cpp:1124, :1192).
        Readback is O(B x N), not O(N^2)."""
        from openr_tpu.ops import spf as spf_ops

        self._snap: GraphSnapshot = _SNAPSHOTS.get(self._ls)
        sid = self._snap.id_of(self._root)
        self._sid = sid
        self._d_all = None
        self._fh = None
        if sid is None:
            return
        srcs, srcs_dev = spf_ops.source_batch(self._snap, sid)
        dev = self._snap.device_arrays()
        packed = spf_ops.spf_view_batch_packed(
            dev.metric, dev.overloaded, srcs_dev
        )
        packed_host = np.asarray(packed)  # one device->host transfer
        bucket = srcs_dev.shape[0]
        self._d = packed_host[:bucket]
        self._fh_batch = packed_host[bucket:].astype(bool)
        self._batch_srcs = srcs  # row i of _d is distances from srcs[i]
        self._row_of = {nid: i for i, nid in enumerate(srcs)}

    def _init_device_sparse(self) -> None:
        """Large-area device backend over resident sliced-ELL bands: the
        same batched {source} + neighbors view as the dense path (packed
        distances + on-device ECMP first hops, one transfer), but no
        dense N x N matrix is ever built — and the bands stay resident on
        the device across rebuilds, so steady-state churn costs one fused
        O(rows x K) scatter + solve dispatch (ops.spf_sparse ELL; the
        incremental-rebuild analogue of reference Decision.cpp:1896-1917)."""
        self._d_all = None
        self._fh = None
        if self._root not in self._ls.get_adjacency_databases():
            self._snap = None
            self._sid = None
            return
        graph, srcs, packed = _ELL_RESIDENT.view_packed(
            self._ls, self._root
        )
        self._snap = _SparseIndexAdapter(graph)
        self._sid = graph.node_index[self._root]
        b = len(srcs)
        self._d = packed[:b]
        self._fh_batch = packed[b:].astype(bool)
        self._batch_srcs = srcs
        # padding repeats the source id; keep the first (real) row
        row_of: Dict[int, int] = {}
        for i, nid in enumerate(srcs):
            row_of.setdefault(nid, i)
        self._row_of = row_of

    # -- native backend ---------------------------------------------------

    def _init_native(self) -> None:
        """Multithreaded C++ Dijkstra core (native/spfcore.cpp)."""
        from openr_tpu.graph import native_spf

        self._snap = _SNAPSHOTS.get(self._ls)
        sid = self._snap.id_of(self._root)
        self._sid = sid
        if sid is None:
            self._d_all = None
            self._fh = None
            return
        self._d_all = native_spf.all_pairs_distances(self._snap)
        self._fh = native_spf.first_hop_matrix(
            self._snap, sid, self._d_all[sid], self._d_all
        ).astype(bool)

    # -- host backend -----------------------------------------------------

    def _init_host(self) -> None:
        self._spf = self._ls.get_spf_result(self._root)

    # -- queries ----------------------------------------------------------

    def is_reachable(self, dst: str) -> bool:
        if self._backend == "device":
            if self._sid is None:
                return dst == self._root
            did = self._snap.id_of(dst)
            return did is not None and self._d[0, did] < INF
        if self._backend == "native":
            if self._sid is None:
                return dst == self._root
            did = self._snap.id_of(dst)
            return did is not None and self._d_all[self._sid, did] < INF
        return dst in self._spf

    def metric_to(self, dst: str) -> Optional[Metric]:
        if self._backend == "device":
            if self._sid is None:
                return 0 if dst == self._root else None
            did = self._snap.id_of(dst)
            if did is None or self._d[0, did] >= INF:
                return None
            return int(self._d[0, did])
        if self._backend == "native":
            if self._sid is None:
                return 0 if dst == self._root else None
            did = self._snap.id_of(dst)
            if did is None or self._d_all[self._sid, did] >= INF:
                return None
            return int(self._d_all[self._sid, did])
        res = self._spf.get(dst)
        return res.metric if res is not None else None

    def next_hops_toward(self, dst: str) -> Set[str]:
        if self._backend == "device":
            if self._sid is None:
                return set()
            did = self._snap.id_of(dst)
            if did is None:
                return set()
            col = self._fh_batch[: len(self._batch_srcs), did]
            return {
                self._snap.node_names[self._batch_srcs[i]]
                for i in np.nonzero(col)[0]
            }
        if self._backend == "native":
            if self._sid is None:
                return set()
            did = self._snap.id_of(dst)
            if did is None:
                return set()
            col = self._fh[:, did]
            return {
                self._snap.node_names[v]
                for v in np.nonzero(col)[0]
                if v < self._snap.n
            }
        res = self._spf.get(dst)
        return set(res.next_hops) if res is not None else set()

    def metric_between(self, a: str, b: str) -> Optional[Metric]:
        """Distance from node a to b, where a is the root or one of its
        neighbors (all LFA needs — reference: Decision.cpp:1192)."""
        if a == b:
            return 0
        if self._backend == "device":
            if self._sid is None:
                return None
            aid, bid = self._snap.id_of(a), self._snap.id_of(b)
            if aid is None or bid is None:
                return None
            row = self._row_of.get(aid)
            if row is None:
                # not in the batch (a is neither root nor neighbor):
                # fall back to the host oracle, correctness over speed.
                # Counted: at scale this is an O(N log N) cliff that must
                # stay at zero on the hot path (LFA only queries
                # neighbors, which the batch always covers).
                SPF_COUNTERS["decision.spf_host_fallback"] += 1
                res = self._ls.get_spf_result(a)
                return res[b].metric if b in res else None
            if self._d[row, bid] >= INF:
                return None
            return int(self._d[row, bid])
        if self._backend == "native":
            if self._d_all is None:
                return None
            aid, bid = self._snap.id_of(a), self._snap.id_of(b)
            if aid is None or bid is None or self._d_all[aid, bid] >= INF:
                return None
            return int(self._d_all[aid, bid])
        res = self._ls.get_spf_result(a)
        return res[b].metric if b in res else None


_SNAPSHOTS = SnapshotCache()


class _SparseIndexAdapter:
    """Gives the sparse device backend the same id_of/node_names surface
    the dense GraphSnapshot provides to the query methods."""

    __slots__ = ("node_names", "node_index", "n", "n_pad", "overloaded")

    def __init__(self, graph):
        # alias, don't copy: the sparse graph's name tuple is shared
        # across patches, so identity survives churn (the labels cache
        # keys on it)
        self.node_names = graph.node_names
        self.node_index = graph.node_index
        self.n = graph.n
        self.n_pad = graph.n_pad
        self.overloaded = graph.overloaded

    def id_of(self, node):
        return self.node_index.get(node)


class _EllResidentCache:
    """Device-resident sliced-ELL state per LinkState identity.

    The bands live on the device across rebuilds (EllState). On a
    topology change the LinkState journal's affected set drives
    ``ell_patch(widen=True)`` and one fused scatter+solve dispatch
    (``EllState.reconverge``); a row outgrowing its slot class widens
    its band in place (node ids stable), so only a node-set change or
    a journal gap forces ``compile_ell`` from scratch. This is the
    sparse analogue of the dense path's SnapshotCache row-patching
    (reference incremental rebuild: openr/decision/Decision.cpp:1896-1917)."""

    def __init__(self) -> None:
        # ls -> (synced topology_version, EllState)
        self._cache = _weakref.WeakKeyDictionary()
        # views the KSP2 engines already computed inside their fused
        # dispatches this build — consumed (popped) by view_packed so
        # SpfView does not pay a second device round trip. Entries are
        # (weakref(ls), version, root, graph, srcs, packed): identity
        # goes through the weakref (id() reuse after gc must never
        # serve a dead graph's rows), consume-once, bounded FIFO (one
        # entry per area engine per build).
        self._preloaded: List[tuple] = []

    def preload_view(self, ls, graph, srcs, packed) -> None:
        self.preload_views(ls, [(graph, srcs, packed)])

    def preload_views(self, ls, views) -> None:
        """Batch preload — the fleet twin's fan-in: every vantage's
        solved view from one batched tenant dispatch lands here so the
        per-vantage ``build_route_db`` calls each consume theirs with
        zero device work. ``views``: [(graph, srcs, packed)]."""
        # dead-graph entries can never match; drop them so MB-scale
        # packed rows don't stay pinned behind a dead LinkState
        self._preloaded = [
            e for e in self._preloaded if e[0]() is not None
        ]
        for graph, srcs, packed in views:
            root = graph.node_names[srcs[0]]
            self._preloaded.append(
                (
                    _weakref.ref(ls), ls.topology_version, root,
                    graph, srcs, packed,
                )
            )
        # bound growth on unconsumed entries — but never below the
        # area count (every area engine preloads BEFORE any view is
        # consumed, so a fixed cap would evict the earliest areas'
        # views each build) nor below THIS batch's size (an N-vantage
        # fleet preload must never evict its own earlier entries)
        cap = max(8, len(self._cache), len(views))
        del self._preloaded[:-cap]

    def has_preloaded(self, ls, root: str) -> bool:
        """True when view_packed would be satisfied by a preloaded
        entry (no device round trip). SpfView's device branch uses
        this to route moderate-N areas through the sparse consumption
        path when the tenant plane already solved them batched."""
        return any(
            e[0]() is ls
            and e[1] == ls.topology_version
            and e[2] == root
            for e in self._preloaded
        )

    def _sync(self, ls: LinkState):
        """Resolve the resident state for ``ls``: returns
        ``(state, pending)`` where ``pending`` is a journaled patched
        EllGraph whose rows are NOT yet applied to the resident bands
        (None when the bands are current or were just fully compiled).
        The cache version is committed by the caller once the pending
        rows actually land (fused into a solve, or via apply_patch)."""
        from openr_tpu.ops import spf_sparse

        entry = self._cache.get(ls)
        if entry is not None:
            version, state = entry
            if version == ls.topology_version:
                return state, None
            affected = ls.affected_since(version)
            patched = (
                spf_sparse.ell_patch(
                    state.graph, ls, sorted(affected), widen=True
                )
                if affected is not None
                else None
            )
            if patched is not None:
                SPF_COUNTERS["decision.ell_patches"] += 1
                return state, patched
        state = spf_sparse.EllState(spf_sparse.compile_ell(ls))
        SPF_COUNTERS["decision.ell_full_compiles"] += 1
        self._cache[ls] = (ls.topology_version, state)
        return state, None

    def state_for(self, ls: LinkState):
        """Synced resident state for solve-free consumers (the KSP2
        masked batches): pending rows are scattered WITHOUT a view
        solve."""
        state, pending = self._sync(ls)
        if pending is not None:
            state.apply_patch(pending)
            self._cache[ls] = (ls.topology_version, state)
        return state

    def view_packed(
        self, ls: LinkState, root: str
    ) -> Tuple[object, List[int], np.ndarray]:
        """Sync the resident bands to ``ls`` and solve the batched
        {root} + neighbors view — pending patch rows ride the FUSED
        scatter+solve dispatch (EllState.reconverge). Returns (EllGraph,
        batch srcs, packed [2B, n_pad] host array: B distance rows then
        B first-hop rows)."""
        from openr_tpu.ops import spf_sparse

        for i, entry in enumerate(self._preloaded):
            ls_ref, version, entry_root, graph, srcs, packed = entry
            if (
                ls_ref() is ls
                and version == ls.topology_version
                and entry_root == root
            ):
                del self._preloaded[i]
                return graph, srcs, packed
        state, pending = self._sync(ls)
        graph = pending if pending is not None else state.graph
        srcs = spf_sparse.ell_source_batch(graph, ls, root)
        packed = np.asarray(state.reconverge(graph, srcs))
        self._cache[ls] = (ls.topology_version, state)
        return state.graph, srcs, packed


_ELL_RESIDENT = _EllResidentCache()


def export_resident_state(ls: LinkState):
    """The version-matched, solved resident ``EllState`` for ``ls`` —
    or None when nothing warm exists. The crash-safe state plane
    (``openr_tpu.state.snapshot``) serializes its warm material from
    this; the EllState itself never leaves the process."""
    entry = _ELL_RESIDENT._cache.get(ls)
    if entry is None:
        return None
    version, state = entry
    if version != ls.topology_version or state._d_dev is None:
        return None
    return state


def fleet_preload_views(ls: LinkState, views) -> None:
    """Install one batched wave's per-vantage solved views (the
    digital twin's fan-in): each ``(graph, srcs, packed)`` triple is
    consumed once by the matching root's next SpfView, so N vantage
    route rebuilds follow one ``world_dispatch`` with zero further
    device work."""
    _ELL_RESIDENT.preload_views(ls, views)


def seed_resident_state(ls: LinkState, state) -> None:
    """Install a rehydrated ``EllState`` as the resident entry for
    ``ls`` at its current topology version (warm-boot path: the state
    plane rebuilt it from a persisted snapshot, digest-gated)."""
    _ELL_RESIDENT._cache[ls] = (ls.topology_version, state)


def reset_device_caches() -> None:
    """Drop every module-level device-derived cache (resident ELL
    bands, preloaded views, compiled graph snapshots). The degradation
    ladder's cold rung calls this when a device solve failed: the next
    build recompiles and re-lands everything from the LinkState alone,
    so a torn dispatch can never leave half-synced resident state
    behind."""
    _ELL_RESIDENT._cache = _weakref.WeakKeyDictionary()
    _ELL_RESIDENT._preloaded = []
    _SNAPSHOTS.invalidate()
    try:
        # lazy: the tenant plane is optional and must not make the
        # cold rung's recovery path depend on its import
        from openr_tpu.ops import world_batch as _world_batch

        _world_batch.reset_world_manager()
    except Exception:
        pass


# externally serialized, never internally locked: every solver is
# created and driven by exactly one plane — Decision's under evb, a
# ctrl handler's (fleet FIB builds, replica absorb) under
# SolverCtrlHandler._lock, the twin's on its one thread. The
# shared-state rule merges all instances by class, so cross-role
# access to one instance is impossible by construction — hence
# "owner" confinement (same contract as WorldManager).
@thread_confined(
    "owner",
    "_advertisers_cache",
    "_build_seq",
    "_ksp2_dsts_cache",
    "_ksp2_engines",
    "_ksp2_tracked",
    "_label_cache",
    "_label_state",
    "_labels_cache",
    "_route_best_cache",
    "_route_cache",
    "_route_cache_meta",
    "_route_entries_cache",
    "_sp_prev_seq",
    "_sp_reuse",
    "_spec_staged",
    "_static_routes_version",
    "_views",
    "backend",
    "best_routes_cache",
    "static_mpls_routes",
)
class SpfSolver:
    """reference: openr/decision/Decision.h:202 SpfSolver (pImpl)."""

    def __init__(
        self,
        my_node_name: str,
        enable_v4: bool = False,
        compute_lfa_paths: bool = False,
        enable_ordered_fib: bool = False,
        bgp_dry_run: bool = False,
        enable_best_route_selection: bool = True,
        backend: str = "device",
        view_cache_cap: Optional[int] = None,
        world_batch: Optional[bool] = None,
    ):
        self.my_node_name = my_node_name
        self.enable_v4 = enable_v4
        self.compute_lfa_paths = compute_lfa_paths
        self.enable_ordered_fib = enable_ordered_fib
        self.bgp_dry_run = bgp_dry_run
        self.enable_best_route_selection = enable_best_route_selection
        self.backend = backend
        # _views LRU capacity (per-graph slots); None -> env/default
        self.view_cache_cap = max(
            1,
            view_cache_cap
            if view_cache_cap is not None
            else VIEW_CACHE_CAP_DEFAULT,
        )
        # multi-area tenant-plane dispatch (ops.world_batch): None ->
        # env opt-in. Off by default — single-area deployments gain
        # nothing and the sequential path is the proven one.
        self.world_batch = (
            world_batch
            if world_batch is not None
            else os.environ.get("OPENR_WORLD_BATCH") == "1"
        )
        self.static_mpls_routes: Dict[int, List[NextHop]] = {}
        self.best_routes_cache: Dict[IpPrefix, BestRouteSelectionResult] = {}
        # root -> (d, fh_matrix, node_names, links_sig,
        # {node: (label, entry)}) for the incremental node-label fast
        # path; per-root so ctrl queries for other nodes don't thrash
        # the hot path's slot
        self._label_cache: Dict[str, tuple] = {}
        # per-graph SPF view cache: ls -> {(version, root): view}.
        # STRONG object keys (no id-reuse aliasing), LRU-bounded: a
        # weak dict can never collect here because each SpfView holds
        # its graph (view._ls), so the value would pin its own key
        self._views: Dict[LinkState, Dict] = {}
        # incremental KSP2 engines keyed weakly by LinkState: a dead
        # area graph must release its engine (resident [n, n] device
        # matrix + path caches) instead of pinning it until eviction
        self._ksp2_engines = _weakref.WeakKeyDictionary()
        # debounce-terminal speculation ledger: ls -> (version, root)
        # staged by speculate_views and not yet consumed by a rebuild.
        # Weakly keyed like _ksp2_engines; the staged view itself lives
        # in _views (it IS the rebuild's cache entry on a hit)
        self._spec_staged = _weakref.WeakKeyDictionary()
        # per-prefix route reuse across churn (driven by the engine's
        # affected set): prefix -> (RibUnicastEntry | None, best result)
        self._route_cache: Dict[IpPrefix, tuple] = {}
        self._route_cache_meta: Optional[tuple] = None
        # nodes the engine's affected set actually covers (its KSP2
        # destinations); reuse is only sound for prefixes whose
        # advertisers all lie inside this set
        self._ksp2_tracked: Set[str] = set()
        # advertiser sets per prefix, cached per prefix_state VERSION:
        # rebuilding them per prefix per event made the reuse loop
        # itself the cost it was meant to avoid (~30us x n_prefixes of
        # entries_for + set building per churn event)
        self._advertisers_cache: Optional[tuple] = None
        # root -> (build seq, {area -> previous build's
        # route-determining signature}) for the SP reuse dirty test
        # (_sp_dirty_nodes): batched distance + first-hop matrices,
        # overload bits, node labels, local-link signature per area
        # ("absent" + versions for areas the root is not in). Bounded
        # like _label_cache.
        self._sp_reuse: Dict[str, tuple] = {}
        # monotonically increasing build counter: ties each cached
        # state to the build that produced it, so the label-route
        # patch below can prove its base state is the SAME build the
        # SP dirty set was diffed against
        self._build_seq = 0
        self._sp_prev_seq: Optional[int] = None
        # per-prefix-state-version KSP2 destination sets (see
        # _prefetch_ksp2_paths)
        self._ksp2_dsts_cache: Optional[tuple] = None
        # previous build's non-None unicast entries / best results —
        # the bulk-reuse path's dict-copy starting point (same
        # lifecycle as _route_cache)
        self._route_entries_cache: Optional[Dict] = None
        self._route_best_cache: Optional[Dict] = None
        # root -> (seq, label_to_node, winners, collision labels,
        # labels-by-node, area): the assembled node-label route map,
        # patchable in O(dirty) when the SP dirty test names the only
        # destinations whose routes could have moved
        self._label_state: Dict[str, tuple] = {}
        # node-label vector cache per live graph: labels only move on
        # an attribute change, so the O(N) rebuild is skipped across
        # metric churn. Weakly keyed (like _ksp2_engines) so a dead
        # area's slot can never alias a recycled id.
        self._labels_cache = _weakref.WeakKeyDictionary()
        # bumped on every static-MPLS mutation: _add_best_paths merges
        # static next hops into self-advertised anycast routes, so the
        # reuse meta must change when they do
        self._static_routes_version = 0

    # -- static MPLS routes ----------------------------------------------

    def update_static_mpls_routes(
        self,
        routes_to_update: Dict[int, List[NextHop]],
        routes_to_delete: List[int],
    ) -> None:
        for label, nhs in routes_to_update.items():
            self.static_mpls_routes[label] = list(nhs)
        for label in routes_to_delete:
            self.static_mpls_routes.pop(label, None)
        self._static_routes_version += 1

    # -- degradation-ladder hooks -----------------------------------------

    def reset_device_state(self) -> None:
        """Discard every solver cache derived from device solves (and
        the module-level resident/compiled caches behind them). The
        ladder's cold rung runs this before a full rebuild so the
        rebuild recomputes everything from the LinkStates alone —
        nothing cached across a failed or torn device dispatch can
        leak into the recovered route database."""
        self._views = {}
        self._ksp2_engines = _weakref.WeakKeyDictionary()
        self._spec_staged = _weakref.WeakKeyDictionary()
        self._labels_cache = _weakref.WeakKeyDictionary()
        self._route_cache = {}
        self._route_cache_meta = None
        self._route_entries_cache = None
        self._route_best_cache = None
        self._advertisers_cache = None
        self._ksp2_dsts_cache = None
        self._ksp2_tracked = set()
        self._sp_reuse = {}
        self._sp_prev_seq = None
        self._label_cache = {}
        self._label_state = {}
        reset_device_caches()
        SPF_COUNTERS["decision.device_state_resets"] += 1

    def set_backend(self, backend: str) -> None:
        """Switch the solve backend. The view/route caches are not
        backend-keyed, so a flip must drop them — otherwise a view
        solved by the old backend would satisfy the new backend's
        cache probe."""
        if backend == self.backend:
            return
        self.backend = backend
        self.reset_device_state()
        SPF_COUNTERS["decision.backend_switches"] += 1

    # -- SPF views --------------------------------------------------------

    def prewarm(self, area_link_states: AreaLinkStates) -> None:
        """Publication-time overlap hook (called by the decision module
        as publications land, BEFORE the debounced rebuild fires): push
        pending topology deltas into the device-resident ELL bands now,
        so the band scatter overlaps the debounce window and the
        previous event's RouteDatabase delta emission instead of
        sitting on the rebuild's critical path. Touches only graphs
        that ALREADY have resident state (never compiles a new one) and
        swallows failures — this is an overlap optimization, not a
        correctness step: the rebuild re-syncs and no-ops when the
        bands are already current.

        Safe to call once per publication in a burst: the EllState
        journal MERGES stacked patches (snapshot-keyed edge deltas, see
        spf_sparse.EllState._note_patch), so N prewarmed publications
        inside one debounce window still leave the debounced rebuild on
        the warm-solve path — burst churn pays one fused dispatch, not
        a forced cold seed."""
        if self.backend != "device":
            return
        for ls in area_link_states.values():
            try:
                entry = _ELL_RESIDENT._cache.get(ls)
                if entry is None or entry[0] == ls.topology_version:
                    continue
                _ELL_RESIDENT.state_for(ls)
                SPF_COUNTERS["decision.ell_prewarms"] += 1
            except Exception:
                continue

    def speculate_views(
        self,
        my_node_name: str,
        area_link_states: AreaLinkStates,
    ) -> int:
        """Debounce-terminal speculation hook (the decision module
        calls this once per saturated debounce window, while the timer
        runs out): under latest-wins, the most likely composition of
        the pending rebuild is the CURRENT coalesced backlog, so solve
        the root's view for it NOW and let the rebuild's ``_view``
        land on a cache hit instead of paying the solve inside the
        route-build critical path. Counted, never silent:
        ``ops.spec_dispatches`` on stage, ``ops.spec_hits`` when the
        rebuild consumes the staged view, ``ops.spec_cancels`` when a
        later publication supersedes it (the committed rebuild then
        re-solves — bit-identical, the view is pure in
        (version, root)). Stands down (``ops.spec_skips``) off-device
        or while any chaos fault is armed: every fault seam belongs to
        the committed path's degradation ladder, and a speculative
        solve consuming a charge would let a fault escape the rung
        that owns it."""
        from openr_tpu.faults.injector import get_injector

        reg = _get_registry()
        if self.backend != "device":
            return 0
        if get_injector().any_armed:
            reg.counter_bump("ops.spec_skips")
            return 0
        staged = 0
        for area in sorted(area_link_states):
            ls = area_link_states[area]
            if not ls.has_node(my_node_name):
                continue
            key = (ls.topology_version, my_node_name)
            prev = self._spec_staged.pop(ls, None)
            if prev == key:
                self._spec_staged[ls] = prev
                continue
            if prev is not None:
                # an earlier stage for this graph died unconsumed
                reg.counter_bump("ops.spec_cancels")
            per_ls = self._views.get(ls)
            if per_ls is not None and key in per_ls:
                continue  # already current: nothing to speculate
            try:
                self._view(area, ls, my_node_name)
            except Exception:
                # abandoned speculation, never an escalation: the
                # committed rebuild owns the retry ladder
                reg.counter_bump("ops.spec_cancels")
                continue
            self._spec_staged[ls] = key
            reg.counter_bump("ops.spec_dispatches")
            staged += 1
        return staged

    def _world_preload(
        self,
        my_node_name: str,
        area_link_states: AreaLinkStates,
    ) -> None:
        """Solve every eligible area's {root}+neighbors view as ONE
        batched tenant-plane dispatch (ops.world_batch) and preload the
        results into the resident-view consumption path, so the
        per-area SpfView constructions below become host-side slices
        instead of N sequential device round trips. Strictly an
        optimization: any failure (or an area already holding a cached
        view) falls back to the per-area sequential solve."""
        if self.backend != "device" or not self.world_batch:
            return
        items = []
        for area in sorted(area_link_states):
            ls = area_link_states[area]
            if not ls.has_node(my_node_name):
                continue
            per_ls = self._views.get(ls)
            if per_ls is not None and (
                (ls.topology_version, my_node_name) in per_ls
            ):
                continue  # cached view: a preload would go unconsumed
            items.append((f"{area}/{my_node_name}", ls, my_node_name))
        if len(items) < 2:
            return  # nothing to batch
        try:
            from openr_tpu.ops import world_batch as _world_batch

            views = _world_batch.get_world_manager().solve_views(
                [(tid, ls, root) for tid, ls, root in items]
            )
            for (_tid, ls, _root), (graph, srcs, packed) in zip(
                items, views
            ):
                _ELL_RESIDENT.preload_view(ls, graph, srcs, packed)
            SPF_COUNTERS["decision.world_preloads"] += 1
        except Exception:
            SPF_COUNTERS["decision.world_preload_failures"] += 1

    def _view(self, area: str, ls: LinkState, root: str) -> SpfView:
        del area  # identity of the LinkState object is the key
        per_ls = self._views.get(ls)
        if per_ls is None:
            per_ls = {}
        else:
            # re-insert on hit: eviction is LRU, not FIFO — with 5+
            # areas a FIFO bound evicts the hottest graph every build,
            # which silently disables the SP dirty test
            del self._views[ls]
        self._views[ls] = per_ls
        while len(self._views) > self.view_cache_cap:
            self._views.pop(next(iter(self._views)))
            SPF_COUNTERS["route_engine.view_evictions"] += 1
        key = (ls.topology_version, root)
        view = per_ls.get(key)
        spec = self._spec_staged.get(ls)
        if spec is not None:
            if view is not None and spec == key:
                # the debounced rebuild consumed the staged view —
                # the speculative solve paid off
                del self._spec_staged[ls]
                _get_registry().counter_bump("ops.spec_hits")
            elif spec[0] != key[0]:
                # the graph moved past the staged version: the
                # speculative solve died unconsumed
                del self._spec_staged[ls]
                _get_registry().counter_bump("ops.spec_cancels")
            # same version, different root (a ctrl query): the staged
            # view stays armed for the rebuild
        if view is None:
            # drop stale versions of this graph
            for k in [k for k in per_ls if k[0] != key[0]]:
                del per_ls[k]
            if self.backend == "device":
                # the degradation ladder's device seam: a cached view
                # never fails (its rows already crossed), a fresh
                # device solve can
                fault_point(FAULT_SPF_SOLVE)
            factory = _SPF_BACKENDS.get(self.backend)
            view = (
                factory(ls, root)
                if factory is not None
                else SpfView(ls, root, self.backend)
            )
            per_ls[key] = view
        return view

    # -- SP route reuse dirty test ----------------------------------------

    def _sp_dirty_nodes(
        self,
        my_node_name: str,
        area_link_states: AreaLinkStates,
    ) -> Tuple[bool, Optional[Set[str]]]:
        """Per-destination change detection for SP_ECMP route reuse.

        A non-KSP2 route from ``my_node_name`` toward advertiser ``a``
        is a pure function of: (1) the prefix entries (version-gated by
        the caller), (2) the batched view's distance and first-hop
        COLUMNS for ``a`` (reachability, best metric, ECMP first hops —
        reference: Decision.cpp:847/:1124), (3) the distance columns of
        the first-hop NEIGHBORS themselves (remaining metric =
        shortest - metric_to(nh), Decision.cpp:1211), (4) the
        advertiser's overload bit (maybeFilterDrainedNodes,
        Decision.cpp:783) and node label (SR PUSH materialization), and
        (5) the local link signature (iface, metric, addresses).

        Compares all of (2)-(5) against the previous build and returns
        ``(stored, dirty)``: ``stored`` is True when a fresh signature
        was recorded (detection will be available next build); ``dirty``
        is the set of node names whose routes MAY have changed, or None
        when no comparable previous signature exists (first build,
        topology re-index, neighbor-set change, non-device backend).

        Multi-area: cross-area best-path selection takes the min over
        every area's view (Decision.cpp:1124 loops areas), so a node is
        clean only if it is clean in EVERY area; per-area signatures are
        compared independently and the dirty sets unioned. An area the
        root is absent from contributes a constant "unreachable" to
        route derivation — it is version-pinned instead of column-
        compared, so the root appearing there (or any churn inside it)
        disables reuse for that build.
        """
        per_area = []
        for area in sorted(area_link_states):
            ls = area_link_states[area]
            if not ls.has_node(my_node_name):
                per_area.append((area, ls, None))
                continue
            view = self._view(area, ls, my_node_name)
            d = getattr(view, "_d", None)
            fh = getattr(view, "_fh_batch", None)
            snap = getattr(view, "_snap", None)
            srcs = getattr(view, "_batch_srcs", None)
            if d is None or fh is None or snap is None or srcs is None:
                return False, None
            per_area.append((area, ls, (view, d, fh, snap, srcs)))
        rec = self._sp_reuse.get(my_node_name)
        prev_all = rec[1] if rec is not None else None
        self._sp_prev_seq = rec[0] if rec is not None else None
        if prev_all is not None and set(prev_all) != {
            a for a, _ls, _v in per_area
        }:
            prev_all = None
        fresh_all: Dict[str, tuple] = {}
        dirty_all: Optional[Set[str]] = (
            set() if prev_all is not None else None
        )
        for area, ls, viewdata in per_area:
            if viewdata is None:
                # root-absent area: pin its whole state
                sig = (
                    "absent",
                    ls.topology_version,
                    ls.attributes_version,
                )
                fresh_all[area] = sig
                if dirty_all is not None and prev_all[area] != sig:
                    dirty_all = None
                continue
            dirty = self._sp_dirty_one_area(
                my_node_name,
                ls,
                viewdata,
                None if prev_all is None else prev_all[area],
                fresh_all,
                area,
            )
            if dirty_all is not None:
                dirty_all = (
                    None if dirty is None else dirty_all | dirty
                )
        # re-insert at the end: eviction below is LRU-by-build, so
        # ctrl queries for other roots can't evict the hot root's slot
        self._sp_reuse.pop(my_node_name, None)
        self._sp_reuse[my_node_name] = (self._build_seq, fresh_all)
        while len(self._sp_reuse) > 8:  # bound ctrl-query growth
            self._sp_reuse.pop(next(iter(self._sp_reuse)))
        return True, dirty_all

    def _sp_dirty_one_area(
        self,
        my_node_name: str,
        ls: LinkState,
        viewdata: tuple,
        prev: Optional[tuple],
        fresh_all: Dict[str, tuple],
        area: str,
    ) -> Optional[Set[str]]:
        """One area's signature build + comparison for _sp_dirty_nodes;
        records the fresh signature into ``fresh_all[area]`` and
        returns the area's dirty set (None = no comparable previous
        signature)."""
        _view, d, fh, snap, srcs = viewdata
        b = len(srcs)
        names = snap.node_names
        n = len(names)
        # the device matrices pad the column (destination) axis to the
        # compiled shape; only the first n columns name real nodes.
        # Without LFA only the ROOT's distance row is ever consumed
        # (metric_to/is_reachable read d[0]; neighbor rows feed LFA,
        # which gates reuse off entirely) — comparing just that row
        # keeps remote churn that reroutes around the root invisible,
        # as it should be.
        d = d[0:1, :n]
        fh = fh[:b, :n]
        links_sig = _local_links_sig(ls, my_node_name)
        # the cache value retains the names referent: identity (shared
        # across snapshot patches on both backends) or content must
        # match, so an id()-reuse after GC can never alias orderings
        lc = self._labels_cache.get(ls)
        if (
            lc is not None
            and lc[0] == ls.attributes_version
            and (lc[1] is names or list(lc[1]) == list(names))
        ):
            labels = lc[2]
        else:
            adj_dbs = ls.get_adjacency_databases()
            labels = np.fromiter(
                (
                    adj_dbs[nm].node_label if nm in adj_dbs else -1
                    for nm in names
                ),
                dtype=np.int64,
                count=n,
            )
            self._labels_cache[ls] = (
                ls.attributes_version,
                names,
                labels,
            )
        ov_arr = getattr(snap, "overloaded", None)
        if ov_arr is not None:
            # snapshots rebuild on every topology change (overload
            # flips included), so their host mask is always current;
            # copy — the sparse resident graph patches it in place
            ov = np.array(ov_arr[:n], dtype=bool)
        else:
            ov = np.fromiter(
                (ls.is_node_overloaded(nm) for nm in names),
                dtype=bool,
                count=n,
            )
        dirty: Optional[Set[str]] = None
        if (
            prev is not None
            and len(prev) == 7
            and prev[4] == links_sig
            and prev[0].shape == d.shape
            and prev[1].shape == fh.shape
            and list(prev[2]) == list(srcs)
            and (
                prev[3] is names or list(prev[3]) == list(names)
            )
        ):
            col_changed = (
                (prev[0] != d).any(axis=0)
                | (prev[1] != fh).any(axis=0)
                | (prev[5] != ov)
                | (prev[6] != labels)
            )
            changed_rows = [
                i
                for i, nid in enumerate(srcs)
                if col_changed[int(nid)]
            ]
            if changed_rows:
                # a shifted neighbor column changes the remaining
                # metric of every destination it first-hops for (old
                # OR new first-hop sets — a hop can appear/vanish)
                dep = (
                    fh[changed_rows].any(axis=0)
                    | prev[1][changed_rows].any(axis=0)
                )
                dirty_mask = col_changed | dep
            else:
                dirty_mask = col_changed
            dirty = {
                str(names[int(i)])
                for i in np.flatnonzero(dirty_mask)
            }
        fresh_all[area] = (
            d.copy(),
            fh.copy(),
            tuple(int(s) for s in srcs),
            names,
            links_sig,
            ov,
            labels,
        )
        return dirty

    # -- route computation ------------------------------------------------

    def build_route_db(
        self,
        my_node_name: str,
        area_link_states: AreaLinkStates,
        prefix_state: PrefixState,
    ) -> Optional[DecisionRouteDb]:
        """Full RIB computation. reference: Decision.cpp:569 buildRouteDb."""
        if not any(ls.has_node(my_node_name) for ls in area_link_states.values()):
            return None

        self._build_seq += 1
        route_db = DecisionRouteDb()
        self.best_routes_cache.clear()
        self._world_preload(my_node_name, area_link_states)
        affected = self._prefetch_ksp2_paths(
            my_node_name, area_link_states, prefix_state
        )

        # Per-prefix route reuse: any prefix whose advertisers provably
        # produce a byte-identical route is served from the cache
        # instead of re-derived (reference analogue: the per-prefix
        # incremental rebuild, Decision.cpp:1896-1917).
        # object references, not id()s: a recycled id on a NEW
        # graph/prefix-state whose version counters matched could alias
        # (plain classes compare by identity; the single slot pins them
        # only until the next build)
        meta = (
            prefix_state,
            prefix_state.version,
            my_node_name,
            self._static_routes_version,
            tuple(
                (a, ls) for a, ls in sorted(area_link_states.items())
            ),
        )
        # two independent change detectors feed the reuse gate:
        # - the KSP2 engine's affected set (covers its tracked
        #   destinations' full path state, second paths included)
        # - the SP dirty test (covers EVERY node's shortest-path route
        #   inputs column-wise; sound only for non-KSP2 prefixes)
        # LFA consumes neighbor-row distances the engine's affected
        # test does not model, so reuse is gated off with it.
        sp_stored, sp_dirty = (
            self._sp_dirty_nodes(my_node_name, area_link_states)
            if not self.compute_lfa_paths
            else (False, None)
        )
        meta_ok = self._route_cache_meta == meta
        reuse = (
            affected
            if (
                affected is not None
                and not self.compute_lfa_paths
                and meta_ok
            )
            else None
        )
        reuse_sp = sp_dirty if meta_ok else None
        populate = (
            affected is not None or sp_stored
        ) and not self.compute_lfa_paths
        self._route_cache_meta = meta if populate else None
        new_cache: Dict[IpPrefix, tuple] = {}

        adv_map = None
        if reuse is not None or reuse_sp is not None:
            # built only when reuse can actually consult it: an
            # LFA-enabled or engine-less solver never reads the map,
            # and building it would re-impose the very per-event cost
            # the cache exists to avoid
            adv_key = (prefix_state, prefix_state.version)
            if (
                self._advertisers_cache is None
                or self._advertisers_cache[0] != adv_key
            ):
                ksp2 = PrefixForwardingAlgorithm.KSP2_ED_ECMP
                amap = {
                    p: (
                        {node for (node, _a) in entries},
                        any(
                            e.forwarding_algorithm == ksp2
                            for e in entries.values()
                        ),
                    )
                    for p, entries in prefix_state.prefixes().items()
                }
                # inverted index + KSP2 set: the bulk-reuse path below
                # touches only the prefixes a dirty node advertises
                adv_index: Dict[str, Set[IpPrefix]] = {}
                ksp2_set: Set[IpPrefix] = set()
                for p, (advs, has_k) in amap.items():
                    if has_k:
                        ksp2_set.add(p)
                    for n in advs:
                        adv_index.setdefault(n, set()).add(p)
                self._advertisers_cache = (
                    adv_key, amap, adv_index, ksp2_set
                )
            adv_map = self._advertisers_cache[1]

        # Bulk reuse: with a valid SP dirty set, only prefixes
        # advertised by a dirty node (or carrying a KSP2 entry, whose
        # gate needs the engine's affected set) can produce a different
        # route — every other cached (entry, best) pair is adopted with
        # TWO C-level dict copies instead of 100k Python-level gate
        # evaluations (~1.7 s/event at 100k).
        iter_prefixes = prefix_state.prefixes()
        bulk = (
            reuse_sp is not None
            and adv_map is not None
            and self._route_entries_cache is not None
        )
        if bulk:
            _key, _amap, adv_index, ksp2_set = self._advertisers_cache
            must: Set[IpPrefix] = set(ksp2_set)
            for n in reuse_sp:
                must |= adv_index.get(n, _EMPTY_PREFIXES)
            route_db.unicast_routes = dict(self._route_entries_cache)
            self.best_routes_cache.update(self._route_best_cache)
            new_cache = dict(self._route_cache)
            for p in must:
                route_db.unicast_routes.pop(p, None)
                self.best_routes_cache.pop(p, None)
                new_cache.pop(p, None)
            # count what actually survived the pops: `must` may name
            # prefixes that were never cached, so set arithmetic
            # (len(cache) - len(must)) under-counts
            SPF_COUNTERS["decision.sp_route_reuses"] += len(new_cache)
            iter_prefixes = must

        for prefix in iter_prefixes:
            if adv_map is not None and prefix in self._route_cache:
                advertisers, has_ksp2 = adv_map[prefix]
                # a cached route is reusable when every input that
                # could change it is provably unchanged:
                # - non-KSP2 prefix + every advertiser clean under the
                #   SP dirty test (column-wise vs the previous build)
                # - OR every advertiser is tracked by the KSP2 engine
                #   and outside its affected set. An advertiser covered
                #   by neither detector forces a re-derive.
                ok = (
                    not has_ksp2
                    and reuse_sp is not None
                    and advertisers.isdisjoint(reuse_sp)
                )
                if ok:
                    SPF_COUNTERS["decision.sp_route_reuses"] += 1
                elif (
                    reuse is not None
                    and advertisers <= self._ksp2_tracked
                    and advertisers.isdisjoint(reuse)
                ):
                    ok = True
                    SPF_COUNTERS["decision.ksp2_route_reuses"] += 1
                if ok:
                    entry, best = self._route_cache[prefix]
                    if best is not None:
                        self.best_routes_cache[prefix] = best
                    if entry is not None:
                        route_db.add_unicast_route(entry)
                    new_cache[prefix] = (entry, best)
                    continue
            entry = self.create_route_for_prefix(
                my_node_name, area_link_states, prefix_state, prefix
            )
            if entry is not None:
                route_db.add_unicast_route(entry)
            if populate:
                new_cache[prefix] = (
                    entry,
                    self.best_routes_cache.get(prefix),
                )
        self._route_cache = new_cache
        if populate:
            # the bulk path's starting point next build: previous
            # non-None unicast entries and best-route results
            self._route_entries_cache = dict(route_db.unicast_routes)
            self._route_best_cache = dict(self.best_routes_cache)
        else:
            self._route_entries_cache = None
            self._route_best_cache = None

        # MPLS routes for node (SR) labels (label routes depend only on
        # the graph, so the raw dirty set applies regardless of the
        # prefix-state meta gate)
        label_to_node = self._build_node_label_routes(
            my_node_name, area_link_states, sp_dirty=sp_dirty
        )
        # bulk-assemble: mpls_routes is a label-keyed dict, so
        # insertion order is irrelevant; per-entry add calls cost
        # ~250 ms/build at 100k
        route_db.mpls_routes.update(
            {lab: ne[1] for lab, ne in label_to_node.items()}
        )

        # MPLS routes for adjacency labels
        for _, ls in sorted(area_link_states.items()):
            for link in ls.ordered_links_from_node(my_node_name):
                top_label = link.adj_label_from(my_node_name)
                if top_label == 0:
                    continue
                if not is_mpls_label_valid(top_label):
                    continue
                route_db.add_mpls_route(
                    RibMplsEntry(
                        top_label,
                        {
                            make_next_hop(
                                link.nh_v6_from(my_node_name),
                                link.iface_from(my_node_name),
                                link.metric_from(my_node_name),
                                MplsAction(action=MplsActionCode.PHP),
                                link.area,
                                link.other_node(my_node_name),
                            )
                        },
                    )
                )

        # static MPLS routes
        for label, nhs in self.static_mpls_routes.items():
            route_db.add_mpls_route(RibMplsEntry(label, set(nhs)))

        return route_db

    # -- node-label routes -------------------------------------------------

    def _derive_label_entry(
        self,
        my_node_name: str,
        node: str,
        area: str,
        area_link_states: AreaLinkStates,
        top_label: int,
    ) -> Optional["RibMplsEntry"]:
        """One node's SR label route (PHP to self; SWAP/PHP toward a
        remote node). None when the node is unreachable."""
        if node == my_node_name:
            nh = make_next_hop(
                BinaryAddress.from_str("::"),
                None,
                0,
                MplsAction(action=MplsActionCode.POP_AND_LOOKUP),
                area,
                None,
            )
            return RibMplsEntry(top_label, {nh})
        metric_nhs = self._get_next_hops_with_metric(
            my_node_name, {(node, area)}, False, area_link_states
        )
        if not metric_nhs[1]:
            return None
        return RibMplsEntry(
            top_label,
            self._get_next_hops(
                my_node_name,
                {(node, area)},
                False,
                False,
                metric_nhs[0],
                metric_nhs[1],
                top_label,
                area_link_states,
                {},
            ),
        )

    def _store_label_state(
        self, my_node_name: str, area: str, result, winners,
        collisions, labels_by,
    ) -> None:
        self._label_state.pop(my_node_name, None)
        self._label_state[my_node_name] = (
            self._build_seq, result, winners, collisions, labels_by,
            area,
        )
        while len(self._label_state) > 8:
            self._label_state.pop(next(iter(self._label_state)))

    def _patch_node_label_routes(
        self,
        my_node_name: str,
        area_link_states: AreaLinkStates,
        dirty: Set[str],
        st: tuple,
    ) -> Optional[Dict[int, Tuple[str, "RibMplsEntry"]]]:
        """O(dirty) update of the node-label route map: re-derive only
        the destinations the SP dirty test names, keeping every other
        (node, entry) pair of the previous build. Returns None when a
        contested label's winner must be recomputed from scratch (the
        losing claimants' entries were never derived), falling back to
        the full loop."""
        ((area, ls),) = area_link_states.items()
        _seq, result, winners, collisions, labels_by, st_area = st
        if st_area != area:
            return None
        adj_dbs = ls.get_adjacency_databases()
        result = dict(result)
        winners = dict(winners)
        labels_by = dict(labels_by)
        collisions = set(collisions)
        for node in sorted(dirty):
            old_label = labels_by.pop(node, None)
            db = adj_dbs.get(node)
            top_label = db.node_label if db is not None else 0
            if top_label == 0 or not is_mpls_label_valid(top_label):
                top_label = None
            entry = (
                self._derive_label_entry(
                    my_node_name, node, area, area_link_states,
                    top_label,
                )
                if top_label is not None
                else None
            )
            was_winner = winners.get(node)
            keeps_label = (
                old_label is not None and old_label == top_label
            )
            if was_winner is not None and not (
                keeps_label and entry is not None
            ):
                # the winner of old_label disappears: a losing
                # claimant (whose entry was never derived) may take
                # over — only the full loop knows who
                if old_label in collisions:
                    return None
                result.pop(old_label, None)
                winners.pop(node, None)
            if top_label is None:
                continue
            labels_by[node] = top_label
            if entry is None:
                continue
            existing = result.get(top_label)
            if existing is not None and existing[0] != node:
                collisions.add(top_label)
                if existing[0] < node:
                    continue  # smaller name keeps the label
                winners.pop(existing[0], None)
            result[top_label] = (node, entry)
            winners[node] = (top_label, entry)
        self._store_label_state(
            my_node_name, area, result, winners, collisions, labels_by
        )
        return result

    def _build_node_label_routes(
        self,
        my_node_name: str,
        area_link_states: AreaLinkStates,
        sp_dirty: Optional[Set[str]] = None,
    ) -> Dict[int, Tuple[str, "RibMplsEntry"]]:
        """SR node-label routes for every labeled node
        (reference: Decision.cpp:600-650 buildRouteDb label loop).

        Incremental fast paths (single-area device backend, no LFA):
        (1) when the SP dirty test proves which destinations' routes
        could have moved, the previous build's assembled map is PATCHED
        in O(dirty) (_patch_node_label_routes) — the O(N) loop never
        runs; (2) otherwise the batched view's column diff marks label
        routes reusable per destination and the loop re-derives only
        the changed ones."""
        label_to_node: Dict[int, Tuple[str, RibMplsEntry]] = {}

        if (
            sp_dirty is not None
            and len(area_link_states) == 1
            and not self.compute_lfa_paths
        ):
            st = self._label_state.get(my_node_name)
            if (
                st is not None
                and self._sp_prev_seq is not None
                and st[0] == self._sp_prev_seq
            ):
                patched = self._patch_node_label_routes(
                    my_node_name, area_link_states, sp_dirty, st
                )
                if patched is not None:
                    return patched

        reusable: Dict[str, Tuple[int, RibMplsEntry]] = {}
        cache_probe = None
        if len(area_link_states) == 1:
            ((area, ls),) = area_link_states.items()
            view = self._view(area, ls, my_node_name)
            d = getattr(view, "_d", None)
            fh = getattr(view, "_fh_batch", None)
            if d is not None and fh is not None and view._snap is not None:
                names = list(view._snap.node_names)
                links_sig = _local_links_sig(ls, my_node_name)
                cache_probe = (d.copy(), fh.copy(), names, links_sig)
                prev = self._label_cache.get(my_node_name)
                if (
                    prev is not None
                    and prev[2] == names
                    and prev[3] == links_sig
                    and prev[0].shape == d.shape
                    and prev[1].shape == fh.shape
                ):
                    # column-wise: a dst is dirty if ANY source row's
                    # distance (root or neighbor — LFA reads neighbor
                    # rows) or first-hop bit changed
                    changed = np.flatnonzero(
                        (prev[0] != d).any(axis=0)
                        | (prev[1] != fh).any(axis=0)
                    )
                    changed_ids = set(int(i) for i in changed)
                    # next-hop derivation subtracts the neighbor's own
                    # distance (remaining = shortest - metric_to(nh)), so
                    # a shifted neighbor row invalidates EVERY label route
                    neighbor_ids = {
                        int(i) for i in view._batch_srcs
                    }
                    if changed_ids.isdisjoint(neighbor_ids):
                        reusable = {
                            node: lab_entry
                            for node, lab_entry in prev[4].items()
                            if (
                                view._snap.id_of(node) is not None
                                and view._snap.id_of(node)
                                not in changed_ids
                            )
                        }

        built: Dict[str, Tuple[int, RibMplsEntry]] = {}
        labels_by: Dict[str, int] = {}
        collisions: Set[int] = set()
        for area, ls in sorted(area_link_states.items()):
            for node, adj_db in sorted(ls.get_adjacency_databases().items()):
                top_label = adj_db.node_label
                if top_label == 0:
                    continue
                if not is_mpls_label_valid(top_label):
                    continue
                labels_by[node] = top_label
                # label collision: deterministically keep the smaller name
                # (reference: Decision.cpp:620-633)
                existing = label_to_node.get(top_label)
                if existing is not None:
                    collisions.add(top_label)
                    if existing[0] < node:
                        continue
                cached = (
                    reusable.get(node)
                    if node != my_node_name
                    else None
                )
                if cached is not None and cached[0] == top_label:
                    label_to_node[top_label] = (node, cached[1])
                    built[node] = cached
                    continue
                entry = self._derive_label_entry(
                    my_node_name, node, area, area_link_states,
                    top_label,
                )
                if entry is None:
                    continue
                label_to_node[top_label] = (node, entry)
                built[node] = (top_label, entry)

        self._label_cache.pop(my_node_name, None)
        if cache_probe is not None:
            # re-insert at the end: eviction below is LRU-by-build
            self._label_cache[my_node_name] = (*cache_probe, built)
            while len(self._label_cache) > 8:  # bound ctrl-query growth
                self._label_cache.pop(next(iter(self._label_cache)))
        if len(area_link_states) == 1:
            ((only_area, _ls),) = area_link_states.items()
            self._store_label_state(
                my_node_name, only_area, label_to_node, built,
                collisions, labels_by,
            )
        return label_to_node

    def create_route_for_prefix(
        self,
        my_node_name: str,
        area_link_states: AreaLinkStates,
        prefix_state: PrefixState,
        prefix: IpPrefix,
    ) -> Optional[RibUnicastEntry]:
        """reference: Decision.cpp:402 createRouteForPrefix."""
        all_entries = prefix_state.entries_for(prefix)
        if not all_entries:
            return None
        self.best_routes_cache.pop(prefix, None)

        # keep only entries from nodes reachable in their own area
        entries: PrefixEntries = dict(all_entries)
        for area, ls in area_link_states.items():
            view = self._view(area, ls, my_node_name)
            for node_area in list(entries):
                node, prefix_area = node_area
                if area == prefix_area and not view.is_reachable(node):
                    del entries[node_area]
        if not entries:
            return None

        if prefix.is_v4 and not self.enable_v4:
            return None

        has_bgp = has_non_bgp = missing_mv = False
        has_self_prepend_label = True
        for node_area, entry in entries.items():
            is_bgp = entry.type == PrefixType.BGP
            has_bgp |= is_bgp
            has_non_bgp |= not is_bgp
            if node_area[0] == my_node_name:
                has_self_prepend_label &= entry.prepend_label is not None
            if is_bgp and entry.mv is None:
                missing_mv = True
        if has_bgp:
            if has_non_bgp and not self.enable_best_route_selection:
                return None
            if missing_mv:
                return None  # a BGP advertiser without its metric vector

        best = self._select_best_routes(
            my_node_name, entries, has_bgp, area_link_states
        )
        if not best.success:
            return None
        if not best.all_node_areas:
            return None
        self.best_routes_cache[prefix] = best

        # routes to self-advertised prefixes are already programmed locally
        # unless we advertise with a prepend label (anycast origination)
        if best.has_node(my_node_name) and not has_self_prepend_label:
            return None

        ftype, falgo = get_prefix_forwarding_type_and_algorithm(
            entries, best.all_node_areas
        )
        if falgo == PrefixForwardingAlgorithm.SP_ECMP:
            return self._select_best_paths_spf(
                my_node_name,
                prefix,
                best,
                entries,
                has_bgp,
                ftype,
                area_link_states,
            )
        if falgo == PrefixForwardingAlgorithm.KSP2_ED_ECMP:
            return self._select_best_paths_ksp2(
                my_node_name,
                prefix,
                best,
                entries,
                has_bgp,
                ftype,
                area_link_states,
            )
        return None

    # -- best route selection --------------------------------------------

    def _select_best_routes(
        self,
        my_node_name: str,
        entries: PrefixEntries,
        is_bgp: bool,
        area_link_states: AreaLinkStates,
    ) -> BestRouteSelectionResult:
        """reference: Decision.cpp:737 selectBestRoutes."""
        ret = BestRouteSelectionResult()
        if self.enable_best_route_selection:
            ret.all_node_areas = select_best_prefix_metrics(entries)
            if ret.all_node_areas:
                ret.best_node_area = select_best_node_area(
                    ret.all_node_areas, my_node_name
                )
            ret.success = True
        elif is_bgp:
            return self._run_best_path_selection_bgp(
                my_node_name, entries, area_link_states
            )
        else:
            ret.all_node_areas = set(entries)
            ret.best_node_area = min(ret.all_node_areas)
            ret.success = True
        return self._maybe_filter_drained_nodes(ret, area_link_states)

    def _run_best_path_selection_bgp(
        self,
        my_node_name: str,
        entries: PrefixEntries,
        area_link_states: AreaLinkStates,
    ) -> BestRouteSelectionResult:
        """MetricVector-ordered BGP best-path selection.
        reference: Decision.cpp:807 runBestPathSelectionBgp."""
        from openr_tpu.decision.metric_vector import (
            CompareResult,
            compare_metric_vectors,
        )

        ret = BestRouteSelectionResult()
        best_vector = None
        for node_area in sorted(entries):
            entry = entries[node_area]
            result = (
                CompareResult.WINNER
                if best_vector is None
                else compare_metric_vectors(entry.mv, best_vector)
            )
            if result in (CompareResult.TIE, CompareResult.ERROR):
                return ret  # ambiguous ordering: no route (success=False)
            if result == CompareResult.WINNER:
                ret.all_node_areas.clear()
            if result in (CompareResult.WINNER, CompareResult.TIE_WINNER):
                best_vector = entry.mv
                ret.best_node_area = node_area
            if result in (
                CompareResult.WINNER,
                CompareResult.TIE_WINNER,
                CompareResult.TIE_LOOSER,
            ):
                ret.all_node_areas.add(node_area)
        ret.success = True
        return self._maybe_filter_drained_nodes(ret, area_link_states)

    def _maybe_filter_drained_nodes(
        self,
        result: BestRouteSelectionResult,
        area_link_states: AreaLinkStates,
    ) -> BestRouteSelectionResult:
        """Drop overloaded (drained) advertisers; if everyone is drained,
        fall back to the unfiltered set. The representative best_node_area
        is kept as originally selected (matches the reference exactly).
        reference: Decision.cpp:783 maybeFilterDrainedNodes."""
        filtered = BestRouteSelectionResult(
            success=result.success,
            all_node_areas={
                (node, area)
                for node, area in result.all_node_areas
                if area not in area_link_states
                or not area_link_states[area].is_node_overloaded(node)
            },
            best_node_area=result.best_node_area,
        )
        return result if not filtered.all_node_areas else filtered

    def _get_min_next_hop_threshold(
        self, best: BestRouteSelectionResult, entries: PrefixEntries
    ) -> Optional[int]:
        """Max of advertised minNexthop requirements among best advertisers.
        reference: Decision.cpp:767 getMinNextHopThreshold."""
        threshold: Optional[int] = None
        for node_area in best.all_node_areas:
            entry = entries.get(node_area)
            if entry is None or entry.min_nexthop is None:
                continue
            if threshold is None or entry.min_nexthop > threshold:
                threshold = entry.min_nexthop
        return threshold

    # -- SP_ECMP ----------------------------------------------------------

    def _select_best_paths_spf(
        self,
        my_node_name: str,
        prefix: IpPrefix,
        best: BestRouteSelectionResult,
        entries: PrefixEntries,
        is_bgp: bool,
        ftype: PrefixForwardingType,
        area_link_states: AreaLinkStates,
    ) -> Optional[RibUnicastEntry]:
        """reference: Decision.cpp:847 selectBestPathsSpf."""
        per_destination = ftype == PrefixForwardingType.SR_MPLS

        # anycast origination: if we also advertise this prefix with a
        # prepend label, don't compute paths toward ourselves
        filtered_best = set(best.all_node_areas)
        if best.has_node(my_node_name) and per_destination:
            for node_area, entry in entries.items():
                if node_area[0] == my_node_name and entry.prepend_label is not None:
                    filtered_best.discard(node_area)
                    break

        min_metric, next_hop_nodes = self._get_next_hops_with_metric(
            my_node_name, filtered_best, per_destination, area_link_states
        )
        if not next_hop_nodes:
            return None

        next_hops = self._get_next_hops(
            my_node_name,
            best.all_node_areas,
            prefix.is_v4,
            per_destination,
            min_metric,
            next_hop_nodes,
            None,
            area_link_states,
            entries,
        )
        return self._add_best_paths(
            my_node_name, prefix, best, entries, is_bgp, next_hops
        )

    # -- KSP2_ED_ECMP -----------------------------------------------------

    def _prefetch_ksp2_paths(
        self,
        my_node_name: str,
        area_link_states: AreaLinkStates,
        prefix_state: PrefixState,
    ) -> Optional[Set[str]]:
        """Batch the KSP2 second-path SPFs onto the device.

        Host semantics (LinkState.get_kth_paths, reference
        LinkState.cpp:763) run ONE Dijkstra per destination over the
        graph minus that destination's first-path links — O(N) SPFs per
        rebuild, the quadratic cliff at fabric scale.

        Moderate N (<= ksp2_engine.ENGINE_MAX_NODES): the incremental
        Ksp2Engine persists paths across churn and re-solves only the
        destinations a change can affect; returns that affected set so
        build_route_db can reuse the untouched routes (None = no reuse
        this build). Larger N: the original per-build chunked masked
        dispatch (every destination, every build).

        Parallel links (LAGs) are first-class: the per-link ELL slots
        (spf_sparse.compile_ell direction="in" + build_edge_masks via
        graph.slot_of) mask individual group members, so no host
        fallback and no engine cold-rebuild on LAG fabrics
        (reference: LinkState.h:82 Link identity).

        Multi-area: one engine per area graph primes that area's paths.
        Route reuse needs EVERY area signaled — KSP2 paths toward a
        best advertiser are computed in every area's graph it appears
        in (_select_best_paths_ksp2 loops all areas), so a single
        unsignaled area's churn could silently change reused routes."""
        if self.backend != "device":
            return None
        # the destination scan is O(total prefix entries): cache it per
        # prefix-state version (at 100k SP-only fabrics it burned
        # ~0.4 s/event discovering an empty set every build)
        dsts_key = (
            prefix_state,
            prefix_state.version,
            my_node_name,
            tuple(sorted(area_link_states)),
        )
        if (
            self._ksp2_dsts_cache is not None
            and self._ksp2_dsts_cache[0] == dsts_key
        ):
            area_dsts = self._ksp2_dsts_cache[1]
        else:
            area_dsts = {area: set() for area in area_link_states}
            for prefix in prefix_state.prefixes():
                for (node, p_area), entry in prefix_state.entries_for(
                    prefix
                ).items():
                    if (
                        entry.forwarding_algorithm
                        == PrefixForwardingAlgorithm.KSP2_ED_ECMP
                        and node != my_node_name
                        and p_area in area_dsts
                    ):
                        area_dsts[p_area].add(node)
            self._ksp2_dsts_cache = (dsts_key, area_dsts)
        if not any(area_dsts.values()):
            return None

        union_affected: Set[str] = set()
        union_tracked: Set[str] = set()
        all_signaled = True
        ran_any = False
        for area, ls in sorted(area_link_states.items()):
            dsts = sorted(area_dsts[area])
            if (
                len(dsts) < KSP2_DEVICE_MIN_DSTS
                or not ls.has_node(my_node_name)
            ):
                all_signaled = False  # area covered by the host path
                continue
            result = self._prefetch_ksp2_area(
                area, ls, my_node_name, dsts
            )
            if result is None:
                all_signaled = False
                continue
            ran_any = True
            union_affected |= result
            union_tracked |= set(dsts)
        if not ran_any or not all_signaled:
            return None
        # a best advertiser's paths are computed in EVERY area graph it
        # appears in: a node advertising in area a but merely PRESENT
        # in area b is untracked by b's engine, so b-churn would never
        # land it in the affected set — its routes must not be reused
        self._ksp2_tracked = {
            n
            for n in union_tracked
            if all(
                (n in area_dsts[a]) or not a_ls.has_node(n)
                for a, a_ls in area_link_states.items()
            )
        } | {my_node_name}
        return union_affected

    def _prefetch_ksp2_area(
        self,
        area: str,
        ls: LinkState,
        my_node_name: str,
        dsts: List[str],
    ) -> Optional[Set[str]]:
        """Device-batch one area's KSP2 paths; returns the affected set
        (cold build = all dsts) or None when the area's paths came from
        the legacy per-build dispatch / host fallback (no reuse)."""
        from openr_tpu.decision import ksp2_engine

        if (
            len(ls.get_adjacency_databases())
            <= ksp2_engine.engine_max_nodes()  # mesh-scaled bound
        ):
            engine = self._ksp2_engines.get(ls)
            if engine is not None and engine.src_name != my_node_name:
                # one engine per graph: keep the hot root's; other
                # roots (ctrl queries) take the host path
                return None
            if engine is None:
                if (
                    ls.get_max_hops_to_node(my_node_name)
                    > KSP2_DEVICE_MAX_HOPS
                ):
                    return None  # high diameter: host Dijkstra wins
                engine = ksp2_engine.Ksp2Engine(my_node_name)
                self._ksp2_engines[ls] = engine
            affected = engine.sync(ls, dsts)
            if engine.valid and engine.ecc_hops > KSP2_DEVICE_MAX_HOPS:
                # diameter grew past the device win: paths for THIS
                # build are already primed; drop the engine so later
                # builds do the cheap host hop check (memoized per
                # topology version) instead of cold-rebuilding each time
                del self._ksp2_engines[ls]
                return affected
            if affected is None and engine.valid:
                # cold build: no reuse this time, but the per-prefix
                # cache built now is valid for the NEXT event — signal
                # "engine ran" with the all-affected set
                return set(dsts)
            return affected

        if ls.get_max_hops_to_node(my_node_name) > KSP2_DEVICE_MAX_HOPS:
            return None  # high-diameter graph: host Dijkstra wins

        from openr_tpu.ops import spf_sparse

        # the same resident device bands the sparse view solves on —
        # incremental ell_patch sync, no band re-upload per dispatch
        state = _ELL_RESIDENT.state_for(ls)
        graph = state.graph
        sid = graph.node_index.get(my_node_name)
        if sid is None:
            return
        # first paths: host trace off the one memoized base SPF
        exclusion_sets = []
        for dst in dsts:
            links: Set[Link] = set()
            for path in ls.get_kth_paths(my_node_name, dst, 1):
                links.update(path)
            exclusion_sets.append(links)

        cands_of = ksp2_engine.make_cands_of(ls, graph.node_index)
        transit_blocked = {
            name
            for name in graph.node_names
            if ls.is_node_overloaded(name) and name != my_node_name
        }

        chunk = _ksp2_chunk(graph)
        for start in range(0, len(dsts), chunk):
            batch_dsts = dsts[start : start + chunk]
            batch_excl = exclusion_sets[start : start + chunk]
            pad = chunk - len(batch_dsts)
            masks, ok = spf_sparse.build_edge_masks(
                graph, batch_excl + [set()] * pad
            )
            drows = spf_sparse.ell_masked_distances_resident(
                state, sid, masks
            )
            SPF_COUNTERS["decision.ksp2_device_batches"] += 1
            for i, dst in enumerate(batch_dsts):
                if not ok[i]:
                    SPF_COUNTERS["decision.ksp2_host_fallbacks"] += 1
                    continue  # host path computes it lazily
                paths = ksp2_engine.trace_paths_from_row(
                    my_node_name,
                    dst,
                    graph.node_index,
                    drows[i].tolist(),
                    batch_excl[i],
                    cands_of,
                    transit_blocked,
                )
                ls.prime_kth_paths(my_node_name, dst, 2, paths)

    def _select_best_paths_ksp2(
        self,
        my_node_name: str,
        prefix: IpPrefix,
        best: BestRouteSelectionResult,
        entries: PrefixEntries,
        is_bgp: bool,
        ftype: PrefixForwardingType,
        area_link_states: AreaLinkStates,
    ) -> Optional[RibUnicastEntry]:
        """2-shortest edge-disjoint ECMP over SR-MPLS tunnels.
        reference: Decision.cpp:908 selectBestPathsKsp2."""
        if ftype != PrefixForwardingType.SR_MPLS:
            return None

        next_hops: Set[NextHop] = set()
        paths: List[Tuple[str, list]] = []  # (area, path)

        for area, ls in sorted(area_link_states.items()):
            for node, best_area in sorted(best.all_node_areas):
                if node == my_node_name and best_area == area:
                    continue
                for path in ls.get_kth_paths(my_node_name, node, 1):
                    paths.append((area, path))

            first_count = len(paths)
            for node, best_area in sorted(best.all_node_areas):
                if area != best_area:
                    continue
                for sec_path in ls.get_kth_paths(my_node_name, node, 2):
                    # avoid double-spray: drop second paths that contain a
                    # first path (anycast in meshes)
                    if any(
                        LinkState.path_a_in_path_b(paths[i][1], sec_path)
                        for i in range(first_count)
                    ):
                        continue
                    paths.append((area, sec_path))

        if not paths:
            return None

        for path_area, path in paths:
            ls = area_link_states[path_area]
            adj_dbs = ls.get_adjacency_databases()
            cost = 0
            labels: List[int] = []
            next_node = my_node_name
            valid = True
            for link in path:
                hop_metric, next_node = link.metric_and_other(next_node)
                cost += hop_metric
                db = adj_dbs.get(next_node)
                if db is None:
                    valid = False
                    break
                labels.append(db.node_label)
            if not valid:
                continue
            # stack order: bottom-of-stack first => reverse the hop
            # order, then drop the first hop's own label (PHP)
            del labels[0]
            labels.reverse()
            dst_entry = entries.get((next_node, path_area))
            if dst_entry is not None and dst_entry.prepend_label is not None:
                labels.insert(0, dst_entry.prepend_label)

            mpls_action = None
            if labels:
                mpls_action = MplsAction(
                    action=MplsActionCode.PUSH, push_labels=tuple(labels)
                )
            first_link = path[0]
            next_hops.add(
                make_next_hop(
                    first_link.nh_v4_from(my_node_name)
                    if prefix.is_v4
                    else first_link.nh_v6_from(my_node_name),
                    first_link.iface_from(my_node_name),
                    cost,
                    mpls_action,
                    first_link.area,
                    first_link.other_node(my_node_name),
                )
            )

        return self._add_best_paths(
            my_node_name, prefix, best, entries, is_bgp, next_hops
        )

    # -- shared route assembly -------------------------------------------

    def _add_best_paths(
        self,
        my_node_name: str,
        prefix: IpPrefix,
        best: BestRouteSelectionResult,
        entries: PrefixEntries,
        is_bgp: bool,
        next_hops: Set[NextHop],
    ) -> Optional[RibUnicastEntry]:
        """reference: Decision.cpp:1033 addBestPaths."""
        min_next_hop = self._get_min_next_hop_threshold(best, entries)
        if min_next_hop is not None and min_next_hop > len(next_hops):
            return None

        if best.has_node(my_node_name):
            prepend_label = None
            for node_area, entry in entries.items():
                if node_area[0] == my_node_name and entry.prepend_label is not None:
                    prepend_label = entry.prepend_label
                    break
            assert prepend_label is not None, "self route without prepend label"
            static_nhs = self.static_mpls_routes.get(prepend_label)
            if static_nhs:
                for nh in static_nhs:
                    next_hops.add(make_next_hop(nh.address, None, 0, None))

        best_entry = entries[best.best_node_area]
        return RibUnicastEntry(
            prefix=prefix,
            nexthops=next_hops,
            best_prefix_entry=best_entry,
            best_area=best.best_node_area[1],
            do_not_install=is_bgp and self.bgp_dry_run,
        )

    # -- next-hop math ----------------------------------------------------

    def _get_min_cost_nodes(
        self, view: SpfView, dst_node_areas: Set[NodeAndArea]
    ) -> Tuple[Metric, Set[str]]:
        """reference: Decision.cpp:1099 getMinCostNodes."""
        shortest: Optional[Metric] = None
        min_cost_nodes: Set[str] = set()
        for dst_node, _ in dst_node_areas:
            metric = view.metric_to(dst_node)
            if metric is None:
                continue
            if shortest is None or shortest >= metric:
                if shortest is None or shortest > metric:
                    shortest = metric
                    min_cost_nodes.clear()
                min_cost_nodes.add(dst_node)
        return (shortest if shortest is not None else -1, min_cost_nodes)

    def _get_next_hops_with_metric(
        self,
        my_node_name: str,
        dst_node_areas: Set[NodeAndArea],
        per_destination: bool,
        area_link_states: AreaLinkStates,
    ) -> Tuple[Metric, Dict[Tuple[str, str], Metric]]:
        """Map (first-hop node, dst) -> remaining distance from that first
        hop to the destination. reference: Decision.cpp:1124."""
        next_hop_nodes: Dict[Tuple[str, str], Metric] = {}
        shortest: Optional[Metric] = None

        for area, ls in sorted(area_link_states.items()):
            view = self._view(area, ls, my_node_name)
            area_min, min_cost_nodes = self._get_min_cost_nodes(
                view, dst_node_areas
            )
            if not min_cost_nodes:
                continue
            if shortest is not None and shortest < area_min:
                continue
            if shortest is None or shortest > area_min:
                shortest = area_min
                next_hop_nodes.clear()

            for dst_node in min_cost_nodes:
                dst_ref = dst_node if per_destination else ""
                for nh in view.next_hops_toward(dst_node):
                    next_hop_nodes[(nh, dst_ref)] = shortest - view.metric_to(nh)

            if self.compute_lfa_paths:
                # RFC 5286 loop-free alternates
                for link in ls.ordered_links_from_node(my_node_name):
                    if not link.is_up():
                        continue
                    neighbor = link.other_node(my_node_name)
                    neighbor_to_here = view.metric_between(
                        neighbor, my_node_name
                    )
                    if neighbor_to_here is None:
                        continue
                    for dst_node, dst_area in dst_node_areas:
                        if area != dst_area:
                            continue
                        dist_from_neighbor = view.metric_between(
                            neighbor, dst_node
                        )
                        if dist_from_neighbor is None:
                            continue
                        if dist_from_neighbor < shortest + neighbor_to_here:
                            key = (
                                neighbor,
                                dst_node if per_destination else "",
                            )
                            prev = next_hop_nodes.get(key)
                            if prev is None or prev > dist_from_neighbor:
                                next_hop_nodes[key] = dist_from_neighbor

        return (shortest if shortest is not None else -1, next_hop_nodes)

    def _get_next_hops(
        self,
        my_node_name: str,
        dst_node_areas: Set[NodeAndArea],
        is_v4: bool,
        per_destination: bool,
        min_metric: Metric,
        next_hop_nodes: Dict[Tuple[str, str], Metric],
        swap_label: Optional[int],
        area_link_states: AreaLinkStates,
        entries: PrefixEntries,
    ) -> Set[NextHop]:
        """Materialize per-link next-hops from the first-hop node map.
        reference: Decision.cpp:1211 getNextHopsThrift."""
        assert next_hop_nodes
        next_hops: Set[NextHop] = set()
        for area, ls in sorted(area_link_states.items()):
            for link in ls.ordered_links_from_node(my_node_name):
                dst_iter = (
                    sorted(dst_node_areas) if per_destination else [("", "")]
                )
                for dst_node, dst_area in dst_iter:
                    if dst_area and dst_area != area:
                        continue
                    neighbor = link.other_node(my_node_name)
                    remaining = next_hop_nodes.get((neighbor, dst_node))
                    if remaining is None or not link.is_up():
                        continue
                    # don't reach dst via another destination node
                    if (
                        dst_node
                        and (neighbor, area) in dst_node_areas
                        and neighbor != dst_node
                    ):
                        continue
                    dist_over_link = link.metric_from(my_node_name) + remaining
                    # without LFA only shortest-path links qualify
                    if not self.compute_lfa_paths and dist_over_link != min_metric:
                        continue

                    mpls_action = None
                    if swap_label is not None:
                        nh_is_dst = (neighbor, area) in dst_node_areas
                        mpls_action = (
                            MplsAction(action=MplsActionCode.PHP)
                            if nh_is_dst
                            else MplsAction(
                                action=MplsActionCode.SWAP,
                                swap_label=swap_label,
                            )
                        )
                    if dst_node:
                        push_labels: List[int] = []
                        dst_entry = entries.get((dst_node, area))
                        if dst_entry is not None and dst_entry.prepend_label is not None:
                            push_labels.append(dst_entry.prepend_label)
                            if not is_mpls_label_valid(push_labels[-1]):
                                continue
                        if dst_node != neighbor:
                            db = ls.get_adjacency_databases().get(dst_node)
                            if db is None:
                                continue
                            push_labels.append(db.node_label)
                            if not is_mpls_label_valid(push_labels[-1]):
                                continue
                        if push_labels:
                            mpls_action = MplsAction(
                                action=MplsActionCode.PUSH,
                                push_labels=tuple(push_labels),
                            )

                    next_hops.add(
                        make_next_hop(
                            link.nh_v4_from(my_node_name)
                            if is_v4
                            else link.nh_v6_from(my_node_name),
                            link.iface_from(my_node_name),
                            dist_over_link,
                            mpls_action,
                            link.area,
                            link.other_node(my_node_name),
                        )
                    )
        return next_hops
