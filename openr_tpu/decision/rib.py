"""Internal RIB representation and route-delta computation.

Behavioral parity with the reference ``openr/decision/RibEntry.h``,
``openr/decision/RouteUpdate.h`` and ``DecisionRouteDb``
(openr/decision/Decision.cpp:112 calculateUpdate / :146 update).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set

from openr_tpu.analysis.annotations import thread_confined
from openr_tpu.types import (
    IpPrefix,
    MplsRoute,
    NextHop,
    PerfEvents,
    PrefixEntry,
    PrefixType,
    RouteDatabase,
    RouteDatabaseDelta,
    UnicastRoute,
)


@dataclass
class RibUnicastEntry:
    """reference: openr/decision/RibEntry.h:37 RibUnicastEntry"""

    prefix: IpPrefix
    nexthops: Set[NextHop] = field(default_factory=set)
    best_prefix_entry: Optional[PrefixEntry] = None
    best_area: str = ""
    do_not_install: bool = False

    def __eq__(self, other) -> bool:
        # equality drives delta computation; best_area intentionally NOT
        # compared (matches reference RibUnicastEntry::operator==)
        return (
            isinstance(other, RibUnicastEntry)
            and self.prefix == other.prefix
            and self.best_prefix_entry == other.best_prefix_entry
            and self.do_not_install == other.do_not_install
            and self.nexthops == other.nexthops
        )

    def to_unicast_route(self) -> UnicastRoute:
        prefix_type = None
        data = None
        if (
            self.best_prefix_entry is not None
            and self.best_prefix_entry.type == PrefixType.BGP
        ):
            prefix_type = PrefixType.BGP
            data = self.best_prefix_entry.data
        return UnicastRoute(
            dest=self.prefix,
            next_hops=tuple(self.nexthops),
            do_not_install=self.do_not_install,
            prefix_type=prefix_type,
            data=data,
        )


@dataclass
class RibMplsEntry:
    """reference: openr/decision/RibEntry.h:93 RibMplsEntry"""

    label: int
    nexthops: Set[NextHop] = field(default_factory=set)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, RibMplsEntry)
            and self.label == other.label
            and self.nexthops == other.nexthops
        )

    def to_mpls_route(self) -> MplsRoute:
        return MplsRoute(top_label=self.label, next_hops=tuple(self.nexthops))


@dataclass
class DecisionRouteUpdate:
    """Route delta published by Decision, consumed by Fib / PrefixManager.
    reference: openr/decision/RouteUpdate.h:22 DecisionRouteUpdate."""

    unicast_routes_to_update: Dict[IpPrefix, RibUnicastEntry] = field(
        default_factory=dict
    )
    unicast_routes_to_delete: List[IpPrefix] = field(default_factory=list)
    mpls_routes_to_update: List[RibMplsEntry] = field(default_factory=list)
    mpls_routes_to_delete: List[int] = field(default_factory=list)
    perf_events: Optional[PerfEvents] = None
    # in-process telemetry trace adopted from the triggering
    # publication (oldest-chain rule, same as perf_events)
    trace: Optional[object] = None

    def empty(self) -> bool:
        return not (
            self.unicast_routes_to_update
            or self.unicast_routes_to_delete
            or self.mpls_routes_to_update
            or self.mpls_routes_to_delete
        )

    def to_route_db_delta(self, node_name: str = "") -> RouteDatabaseDelta:
        return RouteDatabaseDelta(
            this_node_name=node_name,
            unicast_routes_to_update=[
                e.to_unicast_route()
                for _, e in sorted(
                    self.unicast_routes_to_update.items(),
                    key=lambda kv: kv[0],
                )
            ],
            unicast_routes_to_delete=sorted(self.unicast_routes_to_delete),
            mpls_routes_to_update=[
                e.to_mpls_route()
                for e in sorted(
                    self.mpls_routes_to_update, key=lambda e: e.label
                )
            ],
            mpls_routes_to_delete=sorted(self.mpls_routes_to_delete),
            perf_events=self.perf_events,
        )


# a passive container with a single owner at any moment: Decision
# mutates it on whichever role currently drives emission (see
# Decision.route_db's owner confinement) — it carries no lock of its
# own by design
@thread_confined("owner", "unicast_routes", "mpls_routes")
@dataclass
class DecisionRouteDb:
    """The full computed RIB. reference: openr/decision/Decision.h:95."""

    unicast_routes: Dict[IpPrefix, RibUnicastEntry] = field(default_factory=dict)
    mpls_routes: Dict[int, RibMplsEntry] = field(default_factory=dict)

    def add_unicast_route(self, entry: RibUnicastEntry) -> None:
        self.unicast_routes[entry.prefix] = entry

    def add_mpls_route(self, entry: RibMplsEntry) -> None:
        self.mpls_routes[entry.label] = entry

    def calculate_update(self, new_db: "DecisionRouteDb") -> DecisionRouteUpdate:
        """Delta from self -> new_db (reference: Decision.cpp:112)."""
        delta = DecisionRouteUpdate()
        for prefix, entry in new_db.unicast_routes.items():
            old = self.unicast_routes.get(prefix)
            if old is None or old != entry:
                delta.unicast_routes_to_update[prefix] = entry
        for prefix in self.unicast_routes:
            if prefix not in new_db.unicast_routes:
                delta.unicast_routes_to_delete.append(prefix)
        for label, entry in new_db.mpls_routes.items():
            old = self.mpls_routes.get(label)
            if old is None or old != entry:
                delta.mpls_routes_to_update.append(entry)
        for label in self.mpls_routes:
            if label not in new_db.mpls_routes:
                delta.mpls_routes_to_delete.append(label)
        return delta

    def update(self, delta: DecisionRouteUpdate) -> None:
        """Apply a delta in place (reference: Decision.cpp:146)."""
        for prefix in delta.unicast_routes_to_delete:
            self.unicast_routes.pop(prefix, None)
        for prefix, entry in delta.unicast_routes_to_update.items():
            self.unicast_routes[prefix] = entry
        for label in delta.mpls_routes_to_delete:
            self.mpls_routes.pop(label, None)
        for entry in delta.mpls_routes_to_update:
            self.mpls_routes[entry.label] = entry

    def to_route_db(self, node_name: str = "") -> RouteDatabase:
        return RouteDatabase(
            this_node_name=node_name,
            unicast_routes=[
                e.to_unicast_route()
                for _, e in sorted(self.unicast_routes.items(), key=lambda kv: kv[0])
            ],
            mpls_routes=[
                e.to_mpls_route()
                for _, e in sorted(self.mpls_routes.items(), key=lambda kv: kv[0])
            ],
        ).canonicalize()
