"""Prefix advertisement state (the adjacency-RIB of flooded prefixes).

Behavioral parity with the reference ``openr/decision/PrefixState.{h,cpp}``:
``IpPrefix -> {(node, area) -> PrefixEntry}`` with a reverse index, and
changed-prefix sets returned from updates to drive incremental rebuilds.
"""

from __future__ import annotations

from typing import Dict, Set, Tuple

from openr_tpu.analysis.annotations import thread_confined
from openr_tpu.types import IpPrefix, PrefixDatabase, PrefixEntry

NodeAndArea = Tuple[str, str]
PrefixEntries = Dict[NodeAndArea, PrefixEntry]


# externally serialized, never internally locked: every PrefixState is
# owned by one plane (Decision under evb; a ctrl handler's tenant
# views under SolverCtrlHandler._lock). The shared-state rule merges
# instances by class, so cross-role access to one instance is
# impossible by construction — hence "owner" confinement.
@thread_confined("owner", "_node_to_prefixes", "_prefixes", "version")
class PrefixState:
    def __init__(self) -> None:
        self._prefixes: Dict[IpPrefix, PrefixEntries] = {}
        # reverse index: (node, area) -> set of prefixes it advertises
        self._node_to_prefixes: Dict[NodeAndArea, Set[IpPrefix]] = {}
        # bumped whenever any entry actually changes; route caches key
        # their validity off this (solver per-prefix route reuse)
        self.version = 0

    def prefixes(self) -> Dict[IpPrefix, PrefixEntries]:
        return self._prefixes

    def entries_for(self, prefix: IpPrefix) -> PrefixEntries:
        return self._prefixes.get(prefix, {})

    def update_prefix_database(self, db: PrefixDatabase) -> Set[IpPrefix]:
        """Merge one node's prefix database (for one area); returns the set
        of prefixes whose entry set changed (for incremental rebuild).

        ``delete_prefix`` set means withdraw the listed prefixes.
        reference: openr/decision/PrefixState.cpp updatePrefixDatabase.
        """
        node_area: NodeAndArea = (db.this_node_name, db.area)
        changed: Set[IpPrefix] = set()

        if db.delete_prefix:
            for entry in db.prefix_entries:
                if self._remove_entry(node_area, entry.prefix):
                    changed.add(entry.prefix)
            if changed:
                self.version += 1
            return changed

        new_prefixes = {e.prefix: e for e in db.prefix_entries}
        old_prefixes = self._node_to_prefixes.get(node_area, set())

        # removed advertisements
        for prefix in old_prefixes - set(new_prefixes):
            if self._remove_entry(node_area, prefix):
                changed.add(prefix)

        # added / modified advertisements
        for prefix, entry in new_prefixes.items():
            entries = self._prefixes.setdefault(prefix, {})
            if entries.get(node_area) != entry:
                entries[node_area] = entry
                self._node_to_prefixes.setdefault(node_area, set()).add(prefix)
                changed.add(prefix)
        if changed:
            self.version += 1
        return changed

    def delete_prefix_database(self, node: str, area: str) -> Set[IpPrefix]:
        """Withdraw everything a node advertised into an area."""
        node_area = (node, area)
        changed: Set[IpPrefix] = set()
        for prefix in list(self._node_to_prefixes.get(node_area, ())):
            if self._remove_entry(node_area, prefix):
                changed.add(prefix)
        if changed:
            self.version += 1
        return changed

    def _remove_entry(self, node_area: NodeAndArea, prefix: IpPrefix) -> bool:
        entries = self._prefixes.get(prefix)
        if entries is None or node_area not in entries:
            return False
        del entries[node_area]
        if not entries:
            del self._prefixes[prefix]
        prefixes = self._node_to_prefixes.get(node_area)
        if prefixes is not None:
            prefixes.discard(prefix)
            if not prefixes:
                del self._node_to_prefixes[node_area]
        return True

    @staticmethod
    def has_conflicting_forwarding_info(entries: PrefixEntries) -> bool:
        """Advertisers of one prefix disagree on forwarding type or
        algorithm (reference: PrefixState::hasConflictingForwardingInfo)."""
        seen = None
        for entry in entries.values():
            key = (entry.forwarding_type, entry.forwarding_algorithm)
            if seen is None:
                seen = key
            elif key != seen:
                return True
        return False

    def get_node_host_loopbacks(self) -> Dict[NodeAndArea, Set[IpPrefix]]:
        return dict(self._node_to_prefixes)
