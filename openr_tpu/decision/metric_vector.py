"""MetricVector comparison for BGP-injected route selection.

Behavioral parity with the reference ``MetricVectorUtils``
(openr/common/Util.cpp, openr/common/Util.h:503): entities sorted by
priority descending, lexicographic comparison per entity, loner handling
by CompareType (WIN_IF_PRESENT / WIN_IF_NOT_PRESENT /
IGNORE_IF_NOT_PRESENT), tie-breaker entities produce TIE_WINNER/TIE_LOOSER
that only decide if nothing decisive appears, version mismatch is ERROR.
"""

from __future__ import annotations

import enum
from typing import Optional, Tuple

from openr_tpu.types.lsdb import CompareType, MetricEntity, MetricVector

__all__ = [
    "CompareType",
    "MetricEntity",
    "MetricVector",
    "CompareResult",
    "compare_metric_vectors",
]


class CompareResult(enum.IntEnum):
    WINNER = 0
    TIE_WINNER = 1
    TIE = 2
    TIE_LOOSER = 3
    LOOSER = 4
    ERROR = 5


def _invert(r: CompareResult) -> CompareResult:
    return {
        CompareResult.WINNER: CompareResult.LOOSER,
        CompareResult.TIE_WINNER: CompareResult.TIE_LOOSER,
        CompareResult.TIE: CompareResult.TIE,
        CompareResult.TIE_LOOSER: CompareResult.TIE_WINNER,
        CompareResult.LOOSER: CompareResult.WINNER,
        CompareResult.ERROR: CompareResult.ERROR,
    }[r]


def _is_decisive(r: CompareResult) -> bool:
    return r in (CompareResult.WINNER, CompareResult.LOOSER, CompareResult.ERROR)


def _compare_metrics(
    l: Tuple[int, ...], r: Tuple[int, ...], tie_breaker: bool
) -> CompareResult:
    if len(l) != len(r):
        return CompareResult.ERROR
    for lv, rv in zip(l, r):
        if lv > rv:
            return (
                CompareResult.TIE_WINNER if tie_breaker else CompareResult.WINNER
            )
        if lv < rv:
            return (
                CompareResult.TIE_LOOSER if tie_breaker else CompareResult.LOOSER
            )
    return CompareResult.TIE


def _result_for_loner(entity: MetricEntity) -> CompareResult:
    if entity.op == CompareType.WIN_IF_PRESENT:
        return (
            CompareResult.TIE_WINNER
            if entity.is_best_path_tie_breaker
            else CompareResult.WINNER
        )
    if entity.op == CompareType.WIN_IF_NOT_PRESENT:
        return (
            CompareResult.TIE_LOOSER
            if entity.is_best_path_tie_breaker
            else CompareResult.LOOSER
        )
    return CompareResult.TIE  # IGNORE_IF_NOT_PRESENT


def _maybe_update(target: CompareResult, update: CompareResult) -> CompareResult:
    if _is_decisive(update) or target == CompareResult.TIE:
        return update
    return target


def compare_metric_vectors(
    l: Optional[MetricVector], r: Optional[MetricVector]
) -> CompareResult:
    """reference: MetricVectorUtils::compareMetricVectors."""
    if l is None or r is None:
        return CompareResult.ERROR
    if l.version != r.version:
        return CompareResult.ERROR
    result = CompareResult.TIE
    lm, rm = l.sorted_metrics(), r.sorted_metrics()
    li = ri = 0
    while not _is_decisive(result) and li < len(lm) and ri < len(rm):
        le, re = lm[li], rm[ri]
        if le.type == re.type:
            if le.is_best_path_tie_breaker != re.is_best_path_tie_breaker:
                result = _maybe_update(result, CompareResult.ERROR)
            else:
                result = _maybe_update(
                    result,
                    _compare_metrics(
                        le.metric, re.metric, le.is_best_path_tie_breaker
                    ),
                )
            li += 1
            ri += 1
        elif le.priority > re.priority:
            result = _maybe_update(result, _result_for_loner(le))
            li += 1
        elif le.priority < re.priority:
            result = _maybe_update(result, _invert(_result_for_loner(re)))
            ri += 1
        else:
            # same priority, different types: ambiguous
            result = _maybe_update(result, CompareResult.ERROR)
    while not _is_decisive(result) and li < len(lm):
        result = _maybe_update(result, _result_for_loner(lm[li]))
        li += 1
    while not _is_decisive(result) and ri < len(rm):
        result = _maybe_update(result, _invert(_result_for_loner(rm[ri])))
        ri += 1
    return result
